"""graft-lint: repo-specific static analysis + runtime concurrency
sanitizer (mxnet_tpu.analysis, ISSUE 7).

Three layers:
  1. unit fixtures — a known-bad snippet per rule proves every checker
     FIRES, and every suppression form (inline comment, baseline)
     works;
  2. the tier-1 gate — the full mxnet_tpu/ sweep must report ZERO
     non-baselined findings (the `make lint-graft` twin), inside the
     30s budget the bench rider also guards;
  3. the sanitizer — lock-order cycles and non-reentrant re-entry are
     detected typed, no_sync regions raise on device→host syncs, and
     the real PR 5-class hazard (SIGTERM emergency save re-entering
     CheckpointManager._lock) is pinned: the sanitizer catches the
     plain-Condition shape, the shipped RLock-backed condition passes.
"""
import os
import textwrap
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import sanitizer as san
from mxnet_tpu.analysis.core import (Baseline, DEFAULT_BASELINE, REPO_ROOT,
                                     run_detailed)
from mxnet_tpu.observability import metrics as m

ALL_RULES = analysis.ALL_RULES


# -- helpers -----------------------------------------------------------------

def _lint(tmp_path, source, rules=None, baseline=None, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analysis.run(rules, [str(p)], baseline)


@pytest.fixture
def sanitizer():
    """Enable the sanitizer for one test; locks created inside are
    tracked.  State is reset both sides so tests stay independent."""
    san.reset()
    san.enable()
    yield san
    san.disable()
    san.reset()


# known-bad snippets, one per rule ------------------------------------------
BAD_THREAD_SAFETY = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run)

        def _run(self):
            self.count = self.count + 1   # worker write, no lock

        def bump(self):
            self.count = 99               # caller write, no lock
"""

BAD_REENTRY = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self._run)

        def _run(self):
            with self._lock:
                self.flush()

        def flush(self):
            with self._lock:
                pass
"""

BAD_HOST_SYNC = """
    from mxnet_tpu import analysis

    @analysis.hot_path
    def step(grad):
        return grad.asnumpy()
"""

BAD_HOST_SYNC_TRANSITIVE = """
    from mxnet_tpu import analysis

    def _leaf(x):
        return float(x.sum())

    @analysis.hot_path
    def step(x):
        return _leaf(x)
"""

BAD_HOST_SYNC_JIT = """
    import jax

    def _impl(x):
        x.block_until_ready()
        return x

    run = jax.jit(_impl)
"""

BAD_ATOMIC_WRITE = """
    import json

    def save(path, obj):
        with open(path, "w") as f:
            json.dump(obj, f)
"""

GOOD_ATOMIC_IDIOM = """
    import os

    def save(path, data):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(data)
        os.replace(tmp, path)
"""

BAD_ENV_SYNC = """
    import os

    def knob():
        return os.environ.get("MXNET_TOTALLY_UNDOCUMENTED_KNOB", "0")
"""

BAD_METRICS = """
    def record(counter, tenant):
        counter.SERVE_SHED.inc(tenant=f"tenant-{tenant}")
"""

BAD_MEMORY = """
    import jax
    def stage(x, dev):
        return jax.device_put(x, dev)
"""

# ISSUE 15: the jit/program-boundary tier -------------------------------------
BAD_USE_AFTER_DONATE = """
    import jax

    def step(params, grads):
        fn = jax.jit(update, donate_argnums=(0,))
        new = fn(params, grads)
        loss = params["w"].sum()      # read of a donated value
        return new, loss
"""

BAD_DONATE_LOOP = """
    import jax

    def train(params, batches):
        fn = jax.jit(update, donate_argnums=(0,))
        for b in batches:
            out = fn(params, b)       # iter 2 passes a dead buffer
        return out
"""

GOOD_DONATE_REBIND = """
    import jax

    def train(params, batches):
        fn = jax.jit(update, donate_argnums=(0,))
        for b in batches:
            params = fn(params, b)    # rebind kills the taint
        return params
"""

GOOD_DONATE_RESTORE = """
    import jax

    def retry(self, params, grads):
        fn = jax.jit(update, donate_argnums=(0,))
        try:
            out = fn(params, grads)
        except Exception:
            self._restore_snapshot()   # restore idiom revives state
            out = fn(params, grads)
        return out
"""

BAD_DONATE_FACTORY = """
    import jax

    class C:
        def _build_fn(self):
            return jax.jit(update, donate_argnums=(1,))

        def run(self, upd, key, a, b):
            fn = upd.lookup_program(key, lambda: self._build_fn())
            fn(a, b)
            return b.shape            # b went through a donated slot
"""

BAD_RETRACE = """
    import jax

    def per_call(x):
        return jax.jit(lambda v: v + 1)(x)
"""

BAD_RETRACE_LOOP = """
    import jax

    def in_loop(xs):
        out = []
        for x in xs:
            f = jax.jit(step)
            out.append(f(x))
        return out
"""

BAD_RETRACE_KEY = """
    def lookup(self, wvals):
        key = ("update", [str(w.dtype) for w in wvals], id(self))
        return self.lookup_program(key, build)
"""

GOOD_RETRACE_KEY = """
    def lookup(self, wvals):
        key = ("update", tuple(str(w.dtype) for w in wvals), self._uid)
        return self.lookup_program(key, build)
"""

BAD_GATE = """
    from mxnet_tpu.base import getenv

    ENABLED = getenv("MXNET_FIXTURE_GATE", True)

    def hook(x):
        y = compute(x)                # work before the kill switch
        if not ENABLED:
            return x
        return y
"""

BAD_GATE_REREAD = """
    from mxnet_tpu.base import getenv

    ENABLED = getenv("MXNET_FIXTURE_GATE", True)

    def hook(x):
        if not getenv("MXNET_FIXTURE_GATE", True):   # per-call parse
            return x
        return compute(x)
"""

GOOD_GATE = """
    from mxnet_tpu.base import getenv

    ENABLED = getenv("MXNET_FIXTURE_GATE", True)

    def hook(x):
        if not ENABLED:
            return x
        return compute(x)
"""

# the historical shape both PR 12 (wholestep) and PR 14 (mfu) fixed:
# the rider ran, stored its result, and _emit never forwarded it
BAD_BENCH_EMIT = """
    _STATE = {"phase": "start", "img_s": None}

    def _emit(partial):
        out = {"value": _STATE["img_s"]}
        if _STATE.get("lint") is not None:
            out["lint"] = _STATE["lint"]
        print(out)

    def _run():
        _STATE["lint"] = {"ok": True}
        _STATE["mfu"] = {"mfu_pct": 12.0}   # never emitted
"""


# -- each rule fires on its known-bad fixture --------------------------------

def test_thread_safety_fires(tmp_path):
    got = _lint(tmp_path, BAD_THREAD_SAFETY, ["thread-safety"])
    assert len(got) == 1, got
    assert "self.count" in got[0].message
    assert got[0].rule == "thread-safety"


def test_thread_safety_guarded_is_clean(tmp_path):
    guarded = BAD_THREAD_SAFETY.replace(
        "            self.count = self.count + 1   # worker write, no lock",
        "            with self._lock:\n"
        "                self.count = self.count + 1").replace(
        "            self.count = 99               # caller write, no lock",
        "            with self._lock:\n"
        "                self.count = 99")
    assert _lint(tmp_path, guarded, ["thread-safety"]) == []


def test_thread_safety_reentry_fires(tmp_path):
    got = _lint(tmp_path, BAD_REENTRY, ["thread-safety"])
    assert len(got) == 1, got
    assert "re-acquired" in got[0].message
    # RLock version is legal
    ok = BAD_REENTRY.replace("threading.Lock()", "threading.RLock()")
    assert _lint(tmp_path, ok, ["thread-safety"]) == []
    # a BARE Condition() is RLock-backed (threading's documented
    # default) — re-entry through it is legal, not a finding
    cond = BAD_REENTRY.replace("threading.Lock()",
                               "threading.Condition()")
    assert _lint(tmp_path, cond, ["thread-safety"]) == []
    # ...but an explicitly plain-Lock-backed condition is the hazard
    plain = BAD_REENTRY.replace(
        "threading.Lock()", "threading.Condition(threading.Lock())")
    assert len(_lint(tmp_path, plain, ["thread-safety"])) == 1


def test_host_sync_fires(tmp_path):
    got = _lint(tmp_path, BAD_HOST_SYNC, ["host-sync"])
    assert len(got) == 1 and ".asnumpy()" in got[0].message


def test_host_sync_transitive_fires(tmp_path):
    got = _lint(tmp_path, BAD_HOST_SYNC_TRANSITIVE, ["host-sync"])
    assert len(got) == 1, got
    assert "via" in got[0].message and "step" in got[0].message


def test_host_sync_jit_entry_fires(tmp_path):
    got = _lint(tmp_path, BAD_HOST_SYNC_JIT, ["host-sync"])
    assert len(got) == 1 and "block_until_ready" in got[0].message


def test_host_sync_ignores_host_math(tmp_path):
    src = """
        import numpy as np
        from mxnet_tpu import analysis

        @analysis.hot_path
        def step(x, shape):
            n = int(np.prod(shape))
            m = int(x.shape[0])
            return n + m
    """
    assert _lint(tmp_path, src, ["host-sync"]) == []


def test_atomic_write_fires(tmp_path):
    got = _lint(tmp_path, BAD_ATOMIC_WRITE, ["atomic-write"])
    assert len(got) == 2  # the open() and the json.dump
    assert all(f.rule == "atomic-write" for f in got)


def test_atomic_write_idiom_passes(tmp_path):
    assert _lint(tmp_path, GOOD_ATOMIC_IDIOM, ["atomic-write"]) == []
    via_helper = GOOD_ATOMIC_IDIOM.replace(
        "        tmp = path + \".tmp\"\n"
        "        with open(tmp, \"w\") as f:\n"
        "            f.write(data)\n"
        "        os.replace(tmp, path)",
        "        from mxnet_tpu.base import atomic_write\n"
        "        atomic_write(path, data)")
    assert _lint(tmp_path, via_helper, ["atomic-write"]) == []


def test_atomic_write_ignores_reads_and_membufs(tmp_path):
    src = """
        import io
        import json
        import numpy as np

        def load(path):
            with open(path) as f:
                return json.load(f)

        def encode(arr):
            b = io.BytesIO()
            np.save(b, arr)
            return b.getvalue()
    """
    assert _lint(tmp_path, src, ["atomic-write"]) == []


def test_env_sync_fires(tmp_path):
    got = _lint(tmp_path, BAD_ENV_SYNC, ["env-sync"])
    undoc = [f for f in got if "MXNET_TOTALLY_UNDOCUMENTED_KNOB"
             in f.message]
    assert len(undoc) == 1 and "not documented" in undoc[0].message


def test_metrics_hygiene_fires(tmp_path):
    got = _lint(tmp_path, BAD_METRICS, ["metrics-hygiene"])
    assert len(got) == 1 and "f-string" in got[0].message
    # a bounded variable is the allowed idiom
    ok = BAD_METRICS.replace('f"tenant-{tenant}"', "tenant")
    assert _lint(tmp_path, ok, ["metrics-hygiene"]) == []


# -- ISSUE 15: use-after-donate ----------------------------------------------

def test_use_after_donate_fires(tmp_path):
    got = _lint(tmp_path, BAD_USE_AFTER_DONATE, ["use-after-donate"])
    assert len(got) == 1, got
    assert "'params'" in got[0].message
    assert "donated" in got[0].message


def test_use_after_donate_loop_carried(tmp_path):
    """The loop-carried shape: iteration 2 passes the buffer iteration
    1 donated — only a second pass over the loop body sees it."""
    got = _lint(tmp_path, BAD_DONATE_LOOP, ["use-after-donate"])
    assert len(got) == 1, got


def test_use_after_donate_rebind_and_restore_are_kills(tmp_path):
    assert _lint(tmp_path, GOOD_DONATE_REBIND,
                 ["use-after-donate"]) == []
    assert _lint(tmp_path, GOOD_DONATE_RESTORE,
                 ["use-after-donate"]) == []


GOOD_DONATE_SCATTER_RESTORE = """
    import jax

    def train(table, ids, rows):
        fn = jax.jit(update, donate_argnums=(0,))
        fn(table, rows)
        table = table.at[ids].set(rows)   # scatter-restore rebind
        return table
"""

BAD_DONATE_SCATTER_OTHER_TARGET = """
    import jax

    def train(table, ids, rows):
        fn = jax.jit(update, donate_argnums=(0,))
        fn(table, rows)
        fresh = table.at[ids].set(rows)   # no rebind: stale read
        return fresh
"""


def test_use_after_donate_scatter_restore_idiom(tmp_path):
    """ISSUE 20: ``x = x.at[ids].set(...)`` rebinds the donated name to
    the functional scatter result in the same statement — the aliasing
    flow of the whole-step embedding update, not a stale use.
    Scattering into a DIFFERENT name keeps the flagged read."""
    assert _lint(tmp_path, GOOD_DONATE_SCATTER_RESTORE,
                 ["use-after-donate"]) == []
    got = _lint(tmp_path, BAD_DONATE_SCATTER_OTHER_TARGET,
                ["use-after-donate"])
    assert len(got) == 1, got
    assert "'table'" in got[0].message


def test_use_after_donate_through_factory_and_cache(tmp_path):
    """The repo idiom: donation declared in a _build_fn factory,
    resolved through upd.lookup_program(key, lambda: ...)."""
    got = _lint(tmp_path, BAD_DONATE_FACTORY, ["use-after-donate"])
    assert len(got) == 1, got
    assert "'b'" in got[0].message


# -- ISSUE 15: retrace-hazard -------------------------------------------------

def test_retrace_hazard_jit_then_call(tmp_path):
    got = _lint(tmp_path, BAD_RETRACE, ["retrace-hazard"])
    assert any("EVERY call recompiles" in f.message for f in got), got


def test_retrace_hazard_jit_in_loop(tmp_path):
    got = _lint(tmp_path, BAD_RETRACE_LOOP, ["retrace-hazard"])
    assert any("inside a loop" in f.message for f in got), got


def test_retrace_hazard_unstable_cache_key(tmp_path):
    got = _lint(tmp_path, BAD_RETRACE_KEY, ["retrace-hazard"])
    msgs = " | ".join(f.message for f in got)
    assert "unhashable" in msgs and "id(...)" in msgs, got
    # tuple()-coerced comprehensions + counter uids are the blessed
    # idiom (exactly what update_all / wholestep do)
    assert _lint(tmp_path, GOOD_RETRACE_KEY, ["retrace-hazard"]) == []


def test_retrace_hazard_key_resolution_is_scoped(tmp_path):
    """An unrelated local named `key` in ANOTHER function must not
    shadow a blessed cache key (the review-caught false positive:
    file-global name resolution flagged legal code)."""
    src = GOOD_RETRACE_KEY + """
    def other():
        key = [1, 2, 3]     # never a cache key — different scope
        return key
"""
    assert _lint(tmp_path, src, ["retrace-hazard"]) == []


def test_retrace_hazard_blessed_chokepoints_pass():
    """The real compile chokepoints (wholestep, FusedUpdater, serving)
    construct jit programs and must stay clean — the rule is about
    UNblessed sites."""
    got = analysis.run(["retrace-hazard"],
                       [os.path.join(REPO_ROOT, "mxnet_tpu")], None)
    assert got == [], got


# -- ISSUE 15: gate-hygiene ---------------------------------------------------

def test_gate_hygiene_buried_guard_fires(tmp_path):
    got = _lint(tmp_path, BAD_GATE, ["gate-hygiene"])
    assert len(got) == 1 and "buried" in got[0].message


def test_gate_hygiene_per_call_reread_fires(tmp_path):
    got = _lint(tmp_path, BAD_GATE_REREAD, ["gate-hygiene"])
    assert len(got) == 1 and "re-read" in got[0].message


def test_gate_hygiene_guard_first_is_clean(tmp_path):
    assert _lint(tmp_path, GOOD_GATE, ["gate-hygiene"]) == []


def test_gate_hygiene_module_level_read_is_clean(tmp_path):
    """The gate DEFINITION itself (module-level getenv) must not count
    as a re-read."""
    src = GOOD_GATE + """
    RAISE = getenv("MXNET_FIXTURE_GATE_RAISE", True)
"""
    assert _lint(tmp_path, src, ["gate-hygiene"]) == []


# -- ISSUE 15: bench-emit -----------------------------------------------------

def test_bench_emit_fires_on_historical_shape(tmp_path):
    """The exact omission PR 12 (wholestep) and PR 14 (mfu) fixed by
    hand, reconstructed: the rider stores its result, _emit never
    forwards it."""
    got = _lint(tmp_path, BAD_BENCH_EMIT, ["bench-emit"],
                name="bench_fixture.py")
    assert len(got) == 1, got
    assert "'mfu'" in got[0].message and "_emit" in got[0].message


def test_bench_emit_clean_when_forwarded(tmp_path):
    fixed = BAD_BENCH_EMIT.replace(
        '        if _STATE.get("lint") is not None:',
        '        if _STATE.get("mfu") is not None:\n'
        '            out["mfu"] = _STATE["mfu"]\n'
        '        if _STATE.get("lint") is not None:')
    assert _lint(tmp_path, fixed, ["bench-emit"],
                 name="bench_fixture.py") == []


def test_bench_emit_covers_repo_bench_py():
    """The finalize leg audits the REAL bench.py even when the sweep
    paths don't include it — every _STATE rider key must reach _emit
    (this is what caught the probe_attempts omission this PR fixed)."""
    got = analysis.run(["bench-emit"],
                       [os.path.join(REPO_ROOT, "mxnet_tpu")], None)
    assert got == [], got


def test_new_rule_inline_suppression(tmp_path):
    """Both suppression styles work on the new tier too."""
    src = BAD_USE_AFTER_DONATE.replace(
        'loss = params["w"].sum()      # read of a donated value',
        'loss = params["w"].sum()  # graft-lint: disable=use-after-donate')
    assert _lint(tmp_path, src, ["use-after-donate"]) == []
    src2 = BAD_RETRACE.replace(
        "        return jax.jit(lambda v: v + 1)(x)",
        "        # graft-lint: disable=retrace-hazard\n"
        "        return jax.jit(lambda v: v + 1)(x)")
    assert _lint(tmp_path, src2, ["retrace-hazard"]) == []


# -- suppression forms -------------------------------------------------------

def test_inline_suppression_same_line(tmp_path):
    src = BAD_ATOMIC_WRITE.replace(
        'with open(path, "w") as f:',
        'with open(path, "w") as f:  # graft-lint: disable=atomic-write')
    got = _lint(tmp_path, src, ["atomic-write"])
    # the comment covers its own line AND the next (json.dump is two
    # lines down -> still flagged)
    assert len(got) == 1 and "json.dump" in got[0].message


def test_inline_suppression_line_above(tmp_path):
    src = BAD_ATOMIC_WRITE.replace(
        '        with open(path, "w") as f:',
        '        # graft-lint: disable=atomic-write\n'
        '        with open(path, "w") as f:')
    got = _lint(tmp_path, src, ["atomic-write"])
    assert len(got) == 1 and "json.dump" in got[0].message


def test_inline_suppression_rule_list(tmp_path):
    src = BAD_HOST_SYNC.replace(
        "return grad.asnumpy()",
        "return grad.asnumpy()  # graft-lint: disable=host-sync,atomic-write")
    assert _lint(tmp_path, src, ["host-sync"]) == []


def test_suppression_wrong_rule_does_not_hide(tmp_path):
    src = BAD_HOST_SYNC.replace(
        "return grad.asnumpy()",
        "return grad.asnumpy()  # graft-lint: disable=atomic-write")
    assert len(_lint(tmp_path, src, ["host-sync"])) == 1


def test_baseline_suppresses_and_requires_justification(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(BAD_ATOMIC_WRITE))
    active, baselined, _ = run_detailed(["atomic-write"], [str(p)], None)
    assert len(active) == 2
    bl = tmp_path / "baseline.json"
    bl.write_text('{"findings": [{"rule": "atomic-write", '
                  f'"path": "{active[0].path}", "symbol": "save", '
                  '"justification": "test fixture"}]}')
    active2, baselined2, _ = run_detailed(
        ["atomic-write"], [str(p)], str(bl))
    assert active2 == [] and len(baselined2) == 2
    with pytest.raises(ValueError, match="justification"):
        Baseline([{"rule": "x", "path": "y", "symbol": "z"}])


def test_checked_in_baseline_policy():
    """atomic-write and env-sync ship with a near-empty baseline: those
    findings are FIXED, not grandfathered (ISSUE 7 satellite)."""
    bl = Baseline.load(DEFAULT_BASELINE)
    per_rule = bl.rules_present()
    assert per_rule.get("atomic-write", 0) == 0
    assert per_rule.get("env-sync", 0) == 0
    for e in bl.entries:
        assert e["justification"]


# -- the tier-1 gate ---------------------------------------------------------

@pytest.mark.analysis
def test_full_codebase_sweep_clean_and_fast():
    """`make lint-graft` in-process: zero non-baselined findings over
    mxnet_tpu/ at HEAD, inside the 30s budget (bench.py re-checks the
    budget so the gate can't silently outgrow tier-1)."""
    t0 = time.perf_counter()
    active, _, _ = run_detailed(None, ["mxnet_tpu"], DEFAULT_BASELINE)
    dt = time.perf_counter() - t0
    assert active == [], "\n".join(str(f) for f in active)
    assert dt < 30.0, f"sweep took {dt:.1f}s"


@pytest.mark.analysis
def test_cli_exits_nonzero_on_seeded_violations(tmp_path):
    """One seeded violation per rule -> `python -m mxnet_tpu.analysis`
    exits 1 and names every rule (the acceptance-criteria contract for
    make lint-graft, minus the subprocess import cost x5)."""
    from mxnet_tpu.analysis.__main__ import main
    seeds = {"thread-safety": BAD_THREAD_SAFETY,
             "host-sync": BAD_HOST_SYNC,
             "atomic-write": BAD_ATOMIC_WRITE,
             "env-sync": BAD_ENV_SYNC,
             "metrics-hygiene": BAD_METRICS,
             "memory-hygiene": BAD_MEMORY,
             "use-after-donate": BAD_USE_AFTER_DONATE,
             "retrace-hazard": BAD_RETRACE,
             "gate-hygiene": BAD_GATE,
             "bench-emit": BAD_BENCH_EMIT}
    assert set(seeds) == set(ALL_RULES)
    for i, (rule, src) in enumerate(seeds.items()):
        # bench-emit only audits bench-named files
        fname = f"bench_seed_{i}.py" if rule == "bench-emit" \
            else f"seed_{i}.py"
        p = tmp_path / fname
        p.write_text(textwrap.dedent(src))
        rc = main(["--rules", rule, str(p)])
        assert rc == 1, f"rule {rule} did not gate"
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert main([str(clean)]) == 0


# -- sanitizer: lock-order graph ---------------------------------------------

def test_factories_plain_when_disabled():
    assert san.ENABLED is False  # MXNET_SANITIZE defaults off
    assert type(san.make_lock("t")) is type(threading.Lock())
    assert isinstance(san.make_condition("t"), threading.Condition)


def test_lock_order_cycle_detected(sanitizer):
    a = san.make_lock("test.A")
    b = san.make_lock("test.B")
    with a:
        with b:
            pass          # establishes A -> B
    with pytest.raises(san.LockOrderError, match="cycle"):
        with b:
            with a:       # B -> A closes the cycle
                pass
    kinds = [v["kind"] for v in san.violations()]
    assert "cycle" in kinds
    assert ("test.A", "test.B") in san.lock_graph()


def test_consistent_order_is_clean(sanitizer):
    a = san.make_lock("test2.A")
    b = san.make_lock("test2.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert san.violations() == []


def test_nonreentrant_reentry_detected(sanitizer):
    l = san.make_lock("test.reentry")
    with pytest.raises(san.LockOrderError, match="re-acquired"):
        with l:
            with l:
                pass
    assert [v["kind"] for v in san.violations()] == ["reentry"]


def test_rlock_reentry_is_legal(sanitizer):
    l = san.make_rlock("test.rlock")
    with l:
        with l:
            pass
    assert san.violations() == []


def test_tracked_condition_wait_notify(sanitizer):
    cv = san.make_condition("test.cv", reentrant=True)
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=5)
            hits.append("woken")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        hits.append("signal")
        cv.notify_all()
    t.join(timeout=5)
    assert not t.is_alive() and hits == ["signal", "woken"]
    assert san.violations() == []


def test_violation_metrics_and_snapshot(sanitizer):
    base = m.ANALYSIS_LOCK_VIOLATIONS.value
    a, b = san.make_lock("m.A"), san.make_lock("m.B")
    with a:
        with b:
            pass
    try:
        with b:
            with a:
                pass
    except san.LockOrderError:
        pass
    assert m.ANALYSIS_LOCK_VIOLATIONS.value == base + 1
    snap = m.snapshot()["analysis"]
    assert snap["enabled"] is True
    assert snap["cycles"] >= 1
    assert snap["lock_edges"] >= 1


# -- sanitizer: no_sync regions ----------------------------------------------

def test_no_sync_raises_on_asnumpy(sanitizer):
    x = mx.nd.array(np.ones((2, 2), np.float32))
    with pytest.raises(san.SyncViolation, match="asnumpy"):
        with analysis.no_sync("test-region"):
            x.asnumpy()
    assert [v["kind"] for v in san.violations()] == ["sync"]
    # outside the region syncs are fine even with the sanitizer on
    assert x.asnumpy().shape == (2, 2)


def test_no_sync_covers_engine_waits(sanitizer):
    x = mx.nd.array(np.ones((2,), np.float32))
    with pytest.raises(san.SyncViolation):
        with analysis.no_sync():
            x.wait_to_read()


def test_no_sync_nested_labels(sanitizer):
    """Exiting an inner region restores the OUTER region's label, so a
    later violation is attributed to the region actually in force."""
    x = mx.nd.array(np.ones((2,), np.float32))
    with analysis.no_sync("outer"):
        with analysis.no_sync("inner"):
            pass
        with pytest.raises(san.SyncViolation, match="'outer'"):
            x.asnumpy()


def test_no_sync_noop_when_disabled():
    assert san.ENABLED is False
    x = mx.nd.array(np.ones((2,), np.float32))
    with analysis.no_sync():
        assert x.asnumpy().sum() == 2.0   # no raise: region unarmed


# -- the PR 5-class regression: SIGTERM re-entry into CheckpointManager ------

def _mgr_state():
    return {"w": np.arange(8, dtype=np.float32)}


def test_checkpoint_lock_is_signal_reentrant(tmp_path, sanitizer):
    """The shipped fix: CheckpointManager._lock is an RLock-backed
    condition, so an emergency save that re-enters a _lock critical
    section on the SAME thread (exactly what a SIGTERM handler does
    when the signal lands mid-save/wait) completes instead of
    deadlocking.  Run under the sanitizer: zero violations."""
    from mxnet_tpu import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=True)
    done = []

    def emergency_while_lock_held():
        # simulate the handler firing between bytecodes of a _lock
        # critical section: the outer frame holds _lock, the "handler"
        # runs the full synchronous-save path on the same thread
        with mgr._lock:
            mgr.save(7, _mgr_state(), block=True,
                     meta={"emergency": "test"})
            mgr.wait(timeout=30)
        done.append(True)

    t = threading.Thread(target=emergency_while_lock_held, daemon=True)
    t.start()
    t.join(timeout=20)
    assert done, "emergency save deadlocked while holding _lock " \
                 "(the pre-fix plain-Condition behavior)"
    assert mgr.latest_step() == 7
    assert [v for v in san.violations()
            if v["kind"] in ("reentry", "cycle")] == []
    mgr.close()


def test_sanitizer_catches_plain_condition_hazard(tmp_path, sanitizer):
    """Pin #1 on the hazard: with the pre-fix lock shape (a
    NON-reentrant condition), the same handler path is a guaranteed
    same-thread deadlock — the sanitizer raises typed instead of
    hanging the SIGTERM grace window."""
    from mxnet_tpu import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=True)
    mgr._lock = san.make_condition("test.ckpt.plain", reentrant=False)
    with pytest.raises(san.LockOrderError, match="re-acquired"):
        with mgr._lock:
            mgr._raise_pending_error()   # handler path re-enters _lock
    assert "reentry" in [v["kind"] for v in san.violations()]


def test_sanitizer_catches_seq_abba_hazard(tmp_path, sanitizer):
    """Pin #2: the cross-thread half of the hazard.  Pre-fix,
    _next_seq() took _lock while the writer held _write_lock
    (write→queue), while the SIGTERM emergency save acquires
    _write_lock with _lock possibly held on the main thread
    (queue→write) — an ABBA deadlock between the handler and an
    in-flight background write.  Reconstructing the old _next_seq
    shape must trip the lock-order cycle detector; the shipped
    lock-free counter (and the drill test above) stays cycle-free."""
    from mxnet_tpu import checkpoint
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=True)

    def old_next_seq():
        with mgr._lock:          # the pre-fix implementation
            return 1

    # writer-thread shape: seq allocation under the held write lock
    with mgr._write_lock:
        old_next_seq()           # edge: write -> queue
    # handler shape: emergency save while the signal interrupted a
    # _lock critical section
    with pytest.raises(san.LockOrderError, match="cycle"):
        with mgr._lock:
            with mgr._write_lock:   # edge: queue -> write = cycle
                pass
    assert "cycle" in [v["kind"] for v in san.violations()]


def test_emergency_save_with_inflight_async_write(tmp_path, sanitizer):
    """End-to-end on the fixed code: a SIGTERM-style emergency save
    (inside a _lock critical section) completes while the background
    writer has queued work — the exact interleaving the pre-fix shape
    could deadlock — and the sanitizer observes zero cycles."""
    from mxnet_tpu import checkpoint
    slow = {"calls": 0}

    def slow_writes(step, attempt):
        slow["calls"] += 1
        time.sleep(0.05)         # keep the writer busy in _write_lock

    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=True,
                                       fault_hook=slow_writes)
    for step in range(3):
        mgr.save(step, _mgr_state())
    done = []

    def handler():
        with mgr._lock:          # signal landed inside a _lock section
            mgr.save(99, _mgr_state(), block=True,
                     meta={"emergency": "sigterm"})
        mgr.wait(timeout=30)
        done.append(True)

    t = threading.Thread(target=handler, daemon=True)
    t.start()
    t.join(timeout=30)
    assert done, "emergency save deadlocked against the background writer"
    assert mgr.latest_step() == 99
    assert [v for v in san.violations() if v["kind"] == "cycle"] == []
    mgr.close()


# -- donated-buffer poisoning (the ISSUE 15 runtime twin) --------------------

def test_poison_donated_raises_typed_and_set_data_clears(sanitizer):
    x = mx.nd.array(np.ones((2, 2), np.float32))
    n = san.poison_donated("test_dispatch", x)
    assert n == 1
    with pytest.raises(analysis.DonatedBufferError, match="test_dispatch"):
        x.asnumpy()
    with pytest.raises(analysis.DonatedBufferError):
        _ = x.shape
    # repr stays safe for logs/debuggers
    assert "donated buffer" in repr(x._data)
    # the restore path (_set_data) revives the wrapper — exactly where
    # the real buffer would revive
    import jax.numpy as jnp
    x._set_data(jnp.zeros((2, 2), jnp.float32))
    assert x.asnumpy().sum() == 0.0
    assert any(v["kind"] == "donated" for v in san.violations())
    assert san.state()["donated_poisoned"] >= 1


def test_poison_donated_recurses_and_skips_raw(sanitizer):
    a = mx.nd.array(np.ones((2,), np.float32))
    b = mx.nd.array(np.ones((2,), np.float32))
    import jax.numpy as jnp
    raw = jnp.ones((2,))
    n = san.poison_donated("s", [a, (b, None)], raw, {"k": raw})
    assert n == 2  # only the NDArray wrappers carry the sentinel


def test_poison_donated_noop_when_disabled():
    assert san.ENABLED is False
    x = mx.nd.array(np.ones((2,), np.float32))
    assert san.poison_donated("s", x) == 0
    assert x.asnumpy().sum() == 2.0


def test_poison_mapping_in_place(sanitizer):
    import jax.numpy as jnp
    padded = {"data": jnp.ones((4, 3))}
    assert san.poison_mapping("serve_dispatch", padded) == 1
    with pytest.raises(analysis.DonatedBufferError, match="serve_dispatch"):
        _ = padded["data"].shape


def test_wholestep_failed_dispatch_poisons_and_restore_revives(
        tmp_path, monkeypatch, sanitizer):
    """End-to-end drill of the PR 12 incident class: a whole-step
    dispatch fails mid-execution AFTER donation — under MXNET_SANITIZE
    the param wrappers raise typed DonatedBufferError (instead of
    jax's opaque deleted-array RuntimeError), and a
    TrainingSupervisor-style snapshot restore revives them."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.wholestep import WholeStepCompiler
    monkeypatch.setenv("MXNET_WHOLE_STEP", "1")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    st = WholeStepCompiler(net, gluon.loss.L2Loss(), trainer)
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.normal(0, 1, (4, 6)).astype(np.float32))
    y = mx.nd.array(rs.normal(0, 1, (4, 4)).astype(np.float32))
    for _ in range(2):  # step 1 may fall back while shapes materialize
        st.step(x, y)
    assert st.active, st.fallback_reason
    # host snapshot BEFORE the failure (what a supervisor keeps)
    params = {n: p.data().asnumpy()
              for n, p in net.collect_params().items()}

    # make the NEXT dispatch fail as if XLA died mid-execution: wrap
    # every cached program to raise an execution-typed error
    upd = trainer._updaters[0]
    for key, fn in list(upd._fn_cache.items()):
        def boom(*a, _fn=fn, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
        upd._fn_cache[key] = boom
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        st.step(x, y)
    # donated wrappers are poisoned: the first touch is typed and
    # names the dispatch site
    with pytest.raises(analysis.DonatedBufferError, match="whole_step"):
        for p in net.collect_params().values():
            p.data().asnumpy()
    # snapshot restore (the supervisor path: _load_init from host
    # copies) clears the poison
    for n, p in net.collect_params().items():
        p._load_init(mx.nd.array(params[n]), p.list_ctx())
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all()


def test_supervisor_retry_revives_poisoned_buffers(tmp_path, monkeypatch,
                                                   sanitizer):
    """The PR 12 donation-safe-retry path, re-drilled under the
    sanitizer twin: a transient device loss DURING the donated
    whole-step dispatch poisons the wrappers; the TrainingSupervisor's
    snapshot-restore-replay retry revives every one of them and the
    retried step completes — proving restore and poison clear at
    exactly the same points."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.supervisor import TrainingSupervisor
    from mxnet_tpu.gluon.wholestep import WholeStepCompiler
    from mxnet_tpu.resilience import DeviceUnavailableError
    monkeypatch.setenv("MXNET_WHOLE_STEP", "1")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    st = WholeStepCompiler(net, gluon.loss.L2Loss(), trainer)
    sup = TrainingSupervisor(st.step, trainer=trainer, params=net,
                             retries=2, backoff_s=0.0, stall_factor=0,
                             snapshot_steps=1)
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.normal(0, 1, (4, 6)).astype(np.float32))
    y = mx.nd.array(rs.normal(0, 1, (4, 4)).astype(np.float32))
    for _ in range(2):
        sup.step(x, y)
    assert st.active, st.fallback_reason
    # next dispatch dies mid-execution (transient class) exactly once
    upd = trainer._updaters[0]
    fired = {"n": 0}
    for key, fn in list(upd._fn_cache.items()):
        def flaky(*a, _fn=fn, **k):
            if fired["n"] == 0:
                fired["n"] += 1
                raise DeviceUnavailableError("injected tunnel loss")
            return _fn(*a, **k)
        upd._fn_cache[key] = flaky
    loss = sup.step(x, y)   # retried through snapshot restore + replay
    assert fired["n"] == 1
    assert np.isfinite(loss.asnumpy()).all()
    # the poison event was recorded, and nothing is left poisoned
    assert any(v["kind"] == "donated" for v in san.violations())
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all()
    sup.close()


def test_audited_paths_stay_use_after_donate_clean():
    """The ISSUE 15 satellite audit, pinned: the supervisor
    snapshot/restore path and the serving evict/readmit/device_put
    path carry no use-after-donate findings (serving never donates
    weights — only the per-request padded batch — and the supervisor
    rebuilds from host copies; if either changes, this fails before
    the opaque deleted-array error ships)."""
    got = analysis.run(
        ["use-after-donate"],
        [os.path.join(REPO_ROOT, "mxnet_tpu", "gluon", "supervisor.py"),
         os.path.join(REPO_ROOT, "mxnet_tpu", "gluon", "wholestep.py"),
         os.path.join(REPO_ROOT, "mxnet_tpu", "serving"),
         os.path.join(REPO_ROOT, "mxnet_tpu", "optimizer.py")], None)
    assert got == [], got


# -- sanitized serving drill (the chaos-subset acceptance) -------------------

def _tiny_predictor():
    from mxnet_tpu import serving, sym
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                             name="fc")
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = net.infer_shape(data=(4, 3))
    params = {"arg:" + n: mx.nd.array(rs.normal(0, 0.1, s).astype("f"))
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}
    return serving.BucketedPredictor(net, params, {"data": (4, 3)})


@pytest.mark.chaos
def test_threaded_subsystems_zero_lock_cycles(tmp_path, sanitizer):
    """ISSUE 7 acceptance: the threaded serving + checkpoint subsystems,
    exercised together under MXNET_SANITIZE semantics, report ZERO
    lock-order cycles (any cycle raises inside a worker and fails the
    drill typed)."""
    from mxnet_tpu import checkpoint, serving
    pred = _tiny_predictor()
    x = np.ones((1, 3), np.float32)
    with serving.MicroBatcher(pred, max_wait_ms=1.0) as mb:
        # the subsystems really did get tracked locks (created while
        # the sanitizer fixture was enabled)
        assert isinstance(mb._pending_lock, san._TrackedLock)
        outs = [mb.submit(data=x) for _ in range(16)]
        for f in outs:
            f.result(timeout=30)
    srv = serving.ResilientServer(pred, max_wait_ms=1.0)
    try:
        srv.warmup()
        futs = [srv.submit(tenant=f"t{i % 3}", data=x)
                for i in range(24)]
        for f in futs:
            f.result(timeout=30)
        srv.readyz()
    finally:
        srv.close()
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=True)
    for step in range(3):
        mgr.save(step, _mgr_state())
    mgr.wait()
    mgr.close()
    cycles = [v for v in san.violations() if v["kind"] == "cycle"]
    reentry = [v for v in san.violations() if v["kind"] == "reentry"]
    assert cycles == [] and reentry == [], san.violations()
    # an empty order graph is the EXPECTED healthy outcome: these
    # subsystems never nest their tracked locks (nesting is where
    # order edges — and deadlock potential — come from)
