"""Compiled-program contract auditor (mxnet_tpu.analysis.program_audit,
ISSUE 15).

Four contracts, each verified on a synthetic known-bad HLO fixture AND
(where cheap) on a real compiled program:

  1. donation → input-output aliasing, on the REAL whole-step program;
  2. AMP cast coverage (pass/fail fixtures + the real bf16 program);
  3. host-callback detection (a real ``jax.pure_callback`` program);
  4. collective-count mismatch.

Plus the audit lifecycle: contracts without HLO are skipped (strict
mode fails them), the CLI self-audit probe is clean and restores the
program registry, and the sweep+audit pair stays inside the <60s
acceptance budget.
"""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu.analysis import program_audit as pa
from mxnet_tpu.observability import introspect


# -- synthetic HLO fixtures ---------------------------------------------------
_HEADER_ALIAS_2 = (
    'HloModule jit_f, is_scheduled=true, input_output_alias={ {0}: '
    '(0, {}, may-alias), {1}: (3, {}, may-alias) }, '
    'entry_computation_layout={(f32[4]{0})->f32[4]{0}}\n')
_HEADER_NO_ALIAS = (
    'HloModule jit_f, is_scheduled=true, '
    'entry_computation_layout={(f32[4]{0})->f32[4]{0}}\n')

_BODY_BF16 = """\
ENTRY %main (p0: bf16[8,16]) -> bf16[8,16] {
  %p0 = bf16[8,16]{1,0} parameter(0)
  %dot.1 = bf16[8,16]{1,0} dot(bf16[8,16]{1,0} %p0, bf16[16,16]{1,0} %c), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %dot.2 = bf16[8,16]{1,0} dot(bf16[8,16]{1,0} %dot.1, bf16[16,16]{1,0} %c), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
_BODY_F32_LEAK = """\
ENTRY %main (p0: bf16[8,16]) -> f32[8,16] {
  %p0 = bf16[8,16]{1,0} parameter(0)
  %dot.1 = bf16[8,16]{1,0} dot(bf16[8,16]{1,0} %p0, bf16[16,16]{1,0} %c), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %dot.2 = f32[8,16]{1,0} dot(f32[8,16]{1,0} %cvt, f32[16,16]{1,0} %c2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
_BODY_CALLBACK = """\
ENTRY %main (p0: f32[2,2]) -> f32[2,2] {
  %p0 = f32[2,2]{1,0} parameter(0)
  %custom-call.5 = (f32[2,2]{1,0}) custom-call(s64[] %c, f32[2,2]{1,0} %p0), custom_call_target="xla_python_cpu_callback"
  ROOT %gte = f32[2,2]{1,0} get-tuple-element((f32[2,2]{1,0}) %custom-call.5), index=0
}
"""
_BODY_COLLECTIVE = """\
ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %all-reduce.1 = f32[4]{0} all-reduce(f32[4]{0} %p0), replica_groups={}, to_apply=%sum
  ROOT %all-reduce.2 = f32[4]{0} all-reduce(f32[4]{0} %all-reduce.1), replica_groups={}, to_apply=%sum
}
"""


def _rec(hlo, **contracts):
    return {"name": "fixture", "hlo": hlo, "contracts": contracts}


# -- alias-table parsing ------------------------------------------------------

@pytest.mark.program_audit
def test_alias_table_parses_nested_braces():
    """The header nests braces ({0} output indices, {} param
    sub-indices) — the parser must count EVERY entry, not clip at the
    first inner close brace (the bug the first implementation had)."""
    assert pa.parse_alias_table(_HEADER_ALIAS_2 + _BODY_BF16) == [0, 3]
    assert pa.parse_alias_table(_HEADER_NO_ALIAS + _BODY_BF16) == []


@pytest.mark.program_audit
def test_donation_aliasing_fixture_pass_fail():
    good = _rec(_HEADER_ALIAS_2 + _BODY_BF16, donated_leaves=2,
                donate_argnums=(0,))
    assert pa.audit_program(good) == []
    bad = _rec(_HEADER_NO_ALIAS + _BODY_BF16, donated_leaves=2,
               donate_argnums=(0,))
    issues = pa.audit_program(bad)
    assert len(issues) == 1 and issues[0]["check"] == "donation-aliasing"
    assert "degraded to copy" in issues[0]["detail"]


@pytest.mark.program_audit
def test_donation_aliasing_real_jit_program():
    """End-to-end on a real compiled artifact: a donated jit program's
    HLO header carries exactly the aliases the donation asked for."""
    fn = jax.jit(lambda a, b: (a + b, b * 2), donate_argnums=(0,))
    import jax.numpy as jnp
    txt = fn.lower(jnp.ones((4, 4)), jnp.ones((4, 4))).compile().as_text()
    assert pa.parse_alias_table(txt) == [0]


# -- AMP cast coverage --------------------------------------------------------

@pytest.mark.program_audit
def test_amp_coverage_fixtures():
    ok = _rec(_HEADER_NO_ALIAS + _BODY_BF16, amp="bf16")
    assert pa.audit_program(ok) == []
    leak = _rec(_HEADER_NO_ALIAS + _BODY_F32_LEAK, amp="bf16")
    issues = pa.audit_program(leak)
    assert len(issues) == 1 and issues[0]["check"] == "amp-cast-coverage"
    assert "cast leak" in issues[0]["detail"]
    # declared allowance tolerates known-f32 ops
    waived = _rec(_HEADER_NO_ALIAS + _BODY_F32_LEAK, amp="bf16",
                  amp_f32_allowed=1)
    assert pa.audit_program(waived) == []
    cov = pa.amp_cast_coverage(_BODY_F32_LEAK, "bf16")
    assert cov == {"lp": 1, "f32": 1, "coverage": 0.5}


# -- host callbacks -----------------------------------------------------------

@pytest.mark.program_audit
def test_host_callback_fixture_and_real_program():
    clean = _rec(_HEADER_NO_ALIAS + _BODY_BF16, host_callbacks=0)
    assert pa.audit_program(clean) == []
    cb = _rec(_HEADER_NO_ALIAS + _BODY_CALLBACK, host_callbacks=0)
    issues = pa.audit_program(cb)
    assert len(issues) == 1 and issues[0]["check"] == "host-callbacks"
    # a real pure_callback program lowers to the cpu-callback
    # custom-call the detector matches
    import jax.numpy as jnp

    def host(x):
        return np.asarray(x) * 2

    def f(x):
        y = jax.pure_callback(host, jax.ShapeDtypeStruct(x.shape, x.dtype),
                              x)
        return y + 1

    txt = jax.jit(f).lower(jnp.ones((2, 2))).compile().as_text()
    assert pa.count_host_callbacks(txt) >= 1


# -- collective count ---------------------------------------------------------

@pytest.mark.program_audit
def test_collective_count_mismatch():
    match = _rec(_HEADER_NO_ALIAS + _BODY_COLLECTIVE, collectives=2)
    assert pa.audit_program(match) == []
    surprise = _rec(_HEADER_NO_ALIAS + _BODY_COLLECTIVE, collectives=0)
    issues = pa.audit_program(surprise)
    assert len(issues) == 1 and issues[0]["check"] == "collective-count"
    missing = _rec(_HEADER_NO_ALIAS + _BODY_BF16, collectives=3)
    issues = pa.audit_program(missing)
    assert len(issues) == 1 and "plan says 3" in issues[0]["detail"]


# -- lifecycle ----------------------------------------------------------------

@pytest.mark.program_audit
def test_contract_without_hlo_skips_unless_strict():
    rec = {"name": "p", "hlo": None,
           "contracts": {"donated_leaves": 1}}
    issues = pa.audit_program(rec)
    assert len(issues) == 1 and issues[0]["check"] == "hlo-missing" \
        and issues[0]["skipped"]
    lax = pa.audit_programs({"p": rec})
    assert lax["ok"] and lax["skipped"] == ["p"] and lax["checked"] == 0
    strict = pa.audit_programs({"p": rec}, strict=True)
    assert not strict["ok"] and strict["issues"]


@pytest.mark.program_audit
def test_programs_without_contracts_are_ignored():
    rec = {"name": "q", "hlo": _HEADER_NO_ALIAS + _BODY_CALLBACK,
           "contracts": None}
    rep = pa.audit_programs({"q": rec})
    assert rep["ok"] and rep["checked"] == 0 and rep["skipped"] == []


# -- the real whole-step program ----------------------------------------------

def _tiny_wholestep(monkeypatch, steps=3, amp=None):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.wholestep import WholeStepCompiler
    monkeypatch.setenv("MXNET_WHOLE_STEP", "1")
    if amp:
        monkeypatch.setenv("MXNET_AMP", amp)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(8))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    st = WholeStepCompiler(net, gluon.loss.L2Loss(), trainer)
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.normal(0, 1, (4, 8)).astype(np.float32))
    y = mx.nd.array(rs.normal(0, 1, (4, 8)).astype(np.float32))
    for _ in range(steps):
        st.step(x, y)
    return st


@pytest.mark.program_audit
@pytest.mark.introspect
def test_whole_step_donation_aliasing_real(monkeypatch, program_audit):
    """The acceptance pin: on the real whole-step program, EVERY
    donated leaf (params + momentum states + any aux) shows up in the
    lowered program's input_output_alias table."""
    introspect.reset()
    st = _tiny_wholestep(monkeypatch)
    assert st.active, st.fallback_reason
    rec = introspect.programs()["whole_step"]
    leaves = rec["contracts"]["donated_leaves"]
    assert leaves >= 8  # 4 params + 4 momentum states
    aliased = program_audit("whole_step", min_aliased=leaves)
    assert len(aliased) >= leaves
    report = pa.audit_programs(strict=False)
    assert report["ok"], report["issues"]
    assert report["checked"] >= 1


@pytest.mark.program_audit
@pytest.mark.introspect
def test_whole_step_amp_bf16_cast_coverage_real(monkeypatch,
                                                program_audit):
    """MXNET_AMP=bf16: the captured whole-step HLO must contain zero
    f32 dot/conv ops — autocast covered forward AND backward matmuls."""
    introspect.reset()
    st = _tiny_wholestep(monkeypatch, amp="bf16")
    assert st.active, st.fallback_reason
    rec = introspect.programs()["whole_step"]
    assert rec["contracts"]["amp"] == "bf16"
    program_audit("whole_step")
    cov = pa.amp_cast_coverage(rec["hlo"], "bf16")
    assert cov["f32"] == 0 and cov["lp"] >= 2, cov


# -- CLI self-audit -----------------------------------------------------------

@pytest.mark.program_audit
def test_self_audit_clean_and_restores_registry():
    """The --audit-programs probe: builds its own whole-step program,
    audits strict, reports clean — and leaves the host process's
    program registry exactly as it found it."""
    introspect.reset()
    introspect.note_program("marker_prog")
    before = sorted(introspect.programs())
    report = pa.self_audit()
    assert report["ok"], report["issues"]
    assert report["checked"] >= 1
    assert "whole_step" in report["programs"]
    assert sorted(introspect.programs()) == before


@pytest.mark.program_audit
@pytest.mark.analysis
def test_cli_audit_mode_exits_zero():
    """`python -m mxnet_tpu.analysis --audit-only` in-process: the
    lint-graft acceptance leg, minus the subprocess import cost.  Also
    the <60s budget half that rides the audit (the sweep half lives in
    test_analysis.py)."""
    import time
    from mxnet_tpu.analysis.__main__ import main
    t0 = time.perf_counter()
    assert main(["--audit-only"]) == 0
    assert time.perf_counter() - t0 < 30.0
