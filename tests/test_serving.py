"""Inference fast path (mxnet_tpu.serving): bucket routing, padded-forward
parity, zero-recompile serving, micro-batching, donation knobs.

The serving acceptance invariant this file pins (ISSUE 4): after
`warmup()`, serving N requests of mixed batch/sequence sizes inside the
bucket set performs ZERO XLA recompiles and one dispatch per
request/coalesced batch, and padded-bucket outputs are bitwise-equal to
the unpadded forward on the valid rows.
"""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, sym
from mxnet_tpu import observability as obs
from mxnet_tpu.observability import metrics as m
from mxnet_tpu.serving.buckets import (BucketSpec, covering_bucket,
                                       pad_to_shape, pow2_buckets)


# -- helpers -----------------------------------------------------------------

def _mlp_symbol(nin=8, nhid=16, nout=4):
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=nhid,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=nout, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _init_params(net, seed=0, **input_shapes):
    """arg:-prefixed random params for every non-input argument."""
    rs = np.random.RandomState(seed)
    arg_shapes, _, _ = net.infer_shape(**input_shapes)
    params = {}
    for n, s in zip(net.list_arguments(), arg_shapes):
        if n in input_shapes or n.endswith("_label"):
            continue
        params["arg:" + n] = mx.nd.array(
            rs.normal(0, 0.1, s).astype("f"))
    return params


def _mlp_predictor(max_batch=8, **kw):
    net = _mlp_symbol()
    params = _init_params(net, data=(max_batch, 8))
    return serving.BucketedPredictor(
        net, params, {"data": (max_batch, 8)}, **kw), net, params


# -- bucket math -------------------------------------------------------------

def test_pow2_bucket_derivation():
    assert pow2_buckets(8) == [1, 2, 4, 8]
    assert pow2_buckets(9) == [1, 2, 4, 8, 16]   # pow2 ceiling
    assert pow2_buckets(1) == [1]
    assert pow2_buckets(100, lo=16) == [16, 32, 64, 128]
    with pytest.raises(mx.MXNetError):
        pow2_buckets(0)


def test_bucket_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_BUCKETS", "2,16,4")
    spec = BucketSpec({"data": (16, 8)})
    assert spec.batch_buckets == [2, 4, 16]
    monkeypatch.setenv("MXNET_SERVE_BUCKETS", "banana")
    with pytest.raises(mx.MXNetError, match="MXNET_SERVE_BUCKETS"):
        BucketSpec({"data": (16, 8)})


def test_route_picks_smallest_covering_bucket():
    spec = BucketSpec({"data": (16, 8)}, batch_buckets=[2, 4, 8, 16])
    assert spec.route({"data": (1, 8)}) == (2,)
    assert spec.route({"data": (2, 8)}) == (2,)
    assert spec.route({"data": (3, 8)}) == (4,)
    assert spec.route({"data": (9, 8)}) == (16,)
    assert spec.route({"data": (17, 8)}) == (None,)  # caller chunks
    # seq axis: smallest covering on BOTH axes
    spec2 = BucketSpec({"data": (4, 16, 3)}, seq_axes={"data": 1},
                       batch_buckets=[2, 4], seq_buckets=[4, 8, 16])
    assert spec2.route({"data": (1, 5, 3)}) == (2, 8)
    assert spec2.route({"data": (3, 16, 3)}) == (4, 16)
    with pytest.raises(mx.MXNetError, match="seq bucket"):
        spec2.route({"data": (2, 17, 3)})


def test_pad_to_shape():
    a = np.arange(6, dtype="f").reshape(2, 3)
    p = pad_to_shape(a, (4, 3))
    np.testing.assert_array_equal(p[:2], a)
    np.testing.assert_array_equal(p[2:], 0)
    assert pad_to_shape(a, (2, 3)) is not None  # no-op path
    with pytest.raises(mx.MXNetError):
        pad_to_shape(a, (1, 3))  # shrink is not padding


def test_non_batch_major_output_rejected_at_compile():
    """A symbol whose output is not batch-major (here: a scalar whole-
    batch reduction) cannot be served through bucket padding — padding
    would silently dilute the reduction.  Must fail LOUDLY at
    precompile, never corrupt at slice time."""
    net = sym.sum(sym.Variable("data"))  # scalar output
    pred = serving.BucketedPredictor(net, {}, {"data": (4, 3)},
                                     batch_buckets=[4])
    with pytest.raises(mx.MXNetError, match="batch-major"):
        pred.warmup()


def test_kwarg_buckets_validated():
    with pytest.raises(mx.MXNetError, match="positive"):
        BucketSpec({"data": (4, 3)}, batch_buckets=[0, 4])


def test_covering_bucket():
    assert covering_bucket([2, 4, 8], 3) == 4
    assert covering_bucket([2, 4, 8], 8) == 8
    assert covering_bucket([2, 4, 8], 9) is None


# -- padded-forward parity ---------------------------------------------------

def test_padded_output_bitwise_equals_unpadded():
    """Rows of a padded-bucket dispatch must be BITWISE equal to the
    unpadded forward of the same params (the correctness contract that
    makes bucket padding invisible to callers).  Pinned bitwise on the
    CPU tier-1 backend, where XLA kernel choice is shape-stable; on TPU
    the same property holds at ULP level (docs/inference.md)."""
    from mxnet_tpu.predictor import Predictor
    pred, net, params = _mlp_predictor(max_batch=8)
    pred.warmup()
    rs = np.random.RandomState(1)
    for rows in (1, 3, 5, 8):
        x = rs.normal(0, 1, (rows, 8)).astype("f")
        got = pred.predict(x)[0]
        ref_p = Predictor(net.tojson(),
                          {k: v for k, v in params.items()},
                          {"data": (rows, 8)})
        ref_p.set_input("data", x)
        ref_p.forward()
        ref = ref_p.get_output(0)
        assert got.shape == ref.shape == (rows, 4)
        np.testing.assert_array_equal(got, ref)


def test_seq_bucket_valid_region_equals_unpadded():
    """Sequence-axis padding: for a position-independent graph the valid
    (rows, seq) region is bitwise-equal to the unpadded forward."""
    net = sym.Activation(sym.Variable("data") * 2.0 + 1.0,
                         act_type="tanh")
    pred = serving.BucketedPredictor(
        net, {}, {"data": (4, 16, 3)}, seq_axes={"data": 1},
        batch_buckets=[4], seq_buckets=[8, 16])
    pred.warmup()
    exact = serving.BucketedPredictor(
        net, {}, {"data": (3, 10, 3)}, batch_buckets=[3],
        seq_axes={"data": 1}, seq_buckets=[10])
    rs = np.random.RandomState(2)
    x = rs.normal(0, 1, (3, 10, 3)).astype("f")
    got = pred.predict(x)[0]          # (3, 16, 3) routed to bucket (4,16)
    ref = exact.predict(x)[0]         # (3, 10, 3), no padding
    assert got.shape == (3, 16, 3)
    np.testing.assert_array_equal(got[:, :10], ref)


def test_oversize_request_chunks_over_largest_bucket():
    pred, _, _ = _mlp_predictor(max_batch=4)
    pred.warmup()
    rs = np.random.RandomState(3)
    x = rs.normal(0, 1, (11, 8)).astype("f")
    whole = pred.predict(x)[0]
    # chunking slices at the largest bucket (4): compare against direct
    # requests at the same geometry so both sides run the SAME bucket
    # executables (different buckets may pick different XLA kernels,
    # which is allowed to differ in ULPs)
    parts = np.concatenate([pred.predict(x[lo:lo + 4])[0]
                            for lo in range(0, 11, 4)])
    assert whole.shape == (11, 4)
    np.testing.assert_array_equal(whole, parts)


# -- the zero-recompile serving invariant ------------------------------------

@pytest.mark.perf_smoke
def test_zero_recompiles_one_dispatch_after_warmup():
    """ISSUE 4 acceptance gate: after warmup(), mixed-size traffic
    inside the bucket set performs ZERO XLA compiles and exactly ONE
    compiled-program launch per request — no device_puts, no executor
    jit-cache misses (dispatch_counts() + serving counters)."""
    pred, _, _ = _mlp_predictor(max_batch=8)
    pred.warmup()
    assert pred.num_compiled == 4  # buckets 1,2,4,8
    rs = np.random.RandomState(4)
    sizes = [1, 3, 5, 8, 2, 7, 4, 6, 1, 8]
    compiles0 = m.SERVE_COMPILES.value
    misses0 = m.JIT_CACHE_MISSES.value
    c0 = obs.dispatch_counts()
    for rows in sizes:
        out = pred.predict(rs.normal(0, 1, (rows, 8)).astype("f"))
        assert out[0].shape == (rows, 4)
    c1 = obs.dispatch_counts()
    delta = {k: c1.get(k, 0) - c0.get(k, 0)
             for k in c1 if c1.get(k, 0) != c0.get(k, 0)}
    assert m.SERVE_COMPILES.value == compiles0, "hot-path recompile!"
    assert m.JIT_CACHE_MISSES.value == misses0
    assert delta.get("xla:serve", 0) == len(sizes), delta
    assert delta.get("device_put", 0) == 0, delta
    assert delta.get("total", 0) == len(sizes), delta


def test_unwarmed_bucket_compiles_once_then_caches():
    pred, _, _ = _mlp_predictor(max_batch=4)
    rs = np.random.RandomState(5)
    x = rs.normal(0, 1, (3, 8)).astype("f")
    c0 = m.SERVE_COMPILES.value
    pred.predict(x)
    assert m.SERVE_COMPILES.value == c0 + 1  # bucket 4, first sight
    pred.predict(x)
    pred.predict(rs.normal(0, 1, (4, 8)).astype("f"))  # same bucket
    assert m.SERVE_COMPILES.value == c0 + 1


# -- micro-batching ----------------------------------------------------------

def test_microbatcher_coalesces_concurrent_requests():
    pred, _, _ = _mlp_predictor(max_batch=8)
    pred.warmup()
    rs = np.random.RandomState(6)
    xs = [rs.normal(0, 1, (1, 8)).astype("f") for _ in range(6)]
    refs = [pred.predict(x)[0] for x in xs]
    batches0 = m.SERVE_BATCHES.value
    with serving.MicroBatcher(pred, max_wait_ms=200) as bat:
        futs = [bat.submit(data=x) for x in xs]
        outs = [f.result(timeout=30) for f in futs]
    # every caller gets exactly its own rows back (tight tolerance, not
    # bitwise: the coalesced batch runs a LARGER bucket executable than
    # the solo reference, and XLA may pick a different kernel per shape)
    for ref, out in zip(refs, outs):
        np.testing.assert_allclose(ref, out[0], rtol=1e-6, atol=1e-7)
    # 6 concurrent 1-row submits coalesced into far fewer dispatches
    # (first may fire alone before the rest enqueue; 200 ms of hold
    # makes full coalescing overwhelmingly likely)
    assert m.SERVE_BATCHES.value - batches0 <= 3


def test_microbatcher_max_wait_timeout():
    """A lone request must dispatch after ~max_wait, not wait for
    max_batch rows."""
    pred, _, _ = _mlp_predictor(max_batch=8)
    pred.warmup()
    with serving.MicroBatcher(pred, max_wait_ms=30) as bat:
        t0 = time.perf_counter()
        out = bat.predict(data=np.ones((2, 8), "f"))
        dt = time.perf_counter() - t0
    assert out[0].shape == (2, 4)
    assert dt < 10.0  # dispatched on timeout, not starved


def test_microbatcher_max_batch_flush():
    """Row cap flushes a group early; the overflow request leads the
    next group and nothing is lost or duplicated."""
    pred, _, _ = _mlp_predictor(max_batch=8)
    pred.warmup()
    rs = np.random.RandomState(7)
    xs = [rs.normal(0, 1, (2, 8)).astype("f") for _ in range(5)]
    refs = [pred.predict(x)[0] for x in xs]
    with serving.MicroBatcher(pred, max_wait_ms=100, max_batch=4) as bat:
        futs = [bat.submit(data=x) for x in xs]
        outs = [f.result(timeout=30) for f in futs]
    for ref, out in zip(refs, outs):
        np.testing.assert_allclose(ref, out[0], rtol=1e-6, atol=1e-7)


def test_microbatcher_mixed_seq_lengths_coalesce():
    net = sym.Activation(sym.Variable("data") * 2.0 + 1.0,
                         act_type="tanh")
    pred = serving.BucketedPredictor(
        net, {}, {"data": (4, 16, 3)}, seq_axes={"data": 1},
        batch_buckets=[4], seq_buckets=[8, 16]).warmup()
    rs = np.random.RandomState(8)
    a = rs.normal(0, 1, (1, 5, 3)).astype("f")
    b = rs.normal(0, 1, (2, 9, 3)).astype("f")
    ra, rb = pred.predict(a)[0], pred.predict(b)[0]
    with serving.MicroBatcher(pred, max_wait_ms=200) as bat:
        fa, fb = bat.submit(data=a), bat.submit(data=b)
        oa, ob = fa.result(30), fb.result(30)
    # valid regions agree with the solo dispatches (both padded to the
    # group's covering seq bucket, so compare the common valid window)
    np.testing.assert_array_equal(oa[0][:, :5], ra[:, :5])
    np.testing.assert_array_equal(ob[0][:, :9], rb[:, :9])


def test_microbatcher_propagates_errors():
    pred, _, _ = _mlp_predictor(max_batch=4)
    with serving.MicroBatcher(pred, max_wait_ms=10) as bat:
        fut = bat.submit(data=np.ones((1, 9), "f"))  # wrong feature dim
        with pytest.raises(mx.MXNetError, match="dim 1"):
            fut.result(timeout=30)
        # the batcher survives a poisoned request
        out = bat.predict(data=np.ones((1, 8), "f"))
    assert out[0].shape == (1, 4)


def test_microbatcher_bad_request_does_not_poison_group():
    """A malformed submit fails ITS OWN future at enqueue time; a
    well-formed request in the same wait window still succeeds."""
    pred, _, _ = _mlp_predictor(max_batch=8)
    pred.warmup()
    with serving.MicroBatcher(pred, max_wait_ms=200) as bat:
        bad = bat.submit(data=np.ones((2, 9), "f"))   # wrong feature dim
        good = bat.submit(data=np.ones((2, 8), "f"))
        with pytest.raises(mx.MXNetError):
            bad.result(timeout=30)
        out = good.result(timeout=30)
    assert out[0].shape == (2, 4)


def test_microbatcher_oversized_submit_is_async_and_chunked():
    """rows > max_batch rides the dispatcher thread (submit never runs
    the model on the caller's thread) and chunks over the largest
    bucket; results match the direct predict."""
    pred, _, _ = _mlp_predictor(max_batch=4)
    pred.warmup()
    rs = np.random.RandomState(13)
    x = rs.normal(0, 1, (11, 8)).astype("f")
    ref = pred.predict(x)[0]
    with serving.MicroBatcher(pred, max_wait_ms=10, max_batch=4) as bat:
        fut = bat.submit(data=x)
        out = fut.result(timeout=30)
    np.testing.assert_array_equal(ref, out[0])


# -- BucketingModule: switching warmed buckets never recompiles ---------------

def _bucket_sym_gen(seq_len):
    # embedding + pool so every parameter shape is seq-independent (the
    # bucketed-LM shape; per-bucket FC over raw seq would fork weights)
    data = sym.Variable("data")
    emb = sym.Embedding(data, input_dim=16, output_dim=8, name="embed")
    net = sym.FullyConnected(sym.sum(emb, axis=1), num_hidden=4,
                             name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    return net, ("data",), ("softmax_label",)


def _bucket_batch(seq_len, batch=2, fill=1.0):
    return mx.io.DataBatch(
        [mx.nd.ones((batch, seq_len)) * fill], [mx.nd.zeros((batch,))],
        bucket_key=seq_len,
        provide_data=[mx.io.DataDesc("data", (batch, seq_len))],
        provide_label=[mx.io.DataDesc("softmax_label", (batch,))])


@pytest.mark.perf_smoke
def test_bucketing_module_switch_costs_no_recompile():
    """Regression gate: once every bucket has run, switch_bucket is a
    dict lookup — re-visiting buckets adds ZERO jit-cache misses and one
    compiled launch per forward (the reference's shared-memory-pool
    bucketing executor, realized through the shared executor jit
    cache)."""
    mod = mx.mod.BucketingModule(_bucket_sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    b16, b8 = _bucket_batch(16), _bucket_batch(8)
    mod.bind(b16.provide_data, b16.provide_label)
    mod.init_params(mx.init.Xavier())
    # warm both buckets (compiles happen here)
    mod.forward(b16, is_train=False)
    mod.forward(b8, is_train=False)
    misses0 = m.JIT_CACHE_MISSES.value
    c0 = obs.dispatch_counts()
    for i in range(6):  # alternate buckets — the bucketed-LM pattern
        mod.forward(_bucket_batch(16 if i % 2 else 8, fill=float(i)),
                    is_train=False)
        mod.get_outputs()[0].asnumpy()
    c1 = obs.dispatch_counts()
    assert m.JIT_CACHE_MISSES.value == misses0, "bucket switch recompiled"
    delta = {k: c1.get(k, 0) - c0.get(k, 0)
             for k in c1 if c1.get(k, 0) != c0.get(k, 0)}
    assert delta.get("xla:fwd", 0) == 6, delta
    assert delta.get("device_put", 0) == 0, delta


def test_bucketing_module_warmup_buckets():
    """warmup_buckets pre-materializes+compiles a bucket list without
    changing the active bucket; traffic after it adds no misses."""
    mod = mx.mod.BucketingModule(_bucket_sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    b16 = _bucket_batch(16)
    mod.bind(b16.provide_data, b16.provide_label)
    mod.init_params(mx.init.Xavier())
    triples = [
        (s, [mx.io.DataDesc("data", (2, s))],
         [mx.io.DataDesc("softmax_label", (2,))]) for s in (8, 16, 32)]
    mod.warmup_buckets(triples)
    assert mod._active_key == 16  # warmup must not switch the bucket
    misses0 = m.JIT_CACHE_MISSES.value
    for s in (8, 32, 16, 8):
        mod.forward(_bucket_batch(s), is_train=False)
        mod.get_outputs()[0].asnumpy()
    assert m.JIT_CACHE_MISSES.value == misses0
    # training programs are distinct executables: warm them explicitly,
    # then training traffic over the warmed buckets adds no misses
    mod.warmup_buckets(triples, for_training=True)
    misses1 = m.JIT_CACHE_MISSES.value
    for s in (32, 8, 16):
        mod.forward_backward(_bucket_batch(s))
        mod.get_outputs()[0].asnumpy()
    assert m.JIT_CACHE_MISSES.value == misses1


# -- satellites: blob loading, donation, metrics ------------------------------

def test_load_frombuffer_roundtrip(tmp_path):
    rs = np.random.RandomState(9)
    data = {"arg:w": mx.nd.array(rs.normal(0, 1, (3, 4)).astype("f")),
            "aux:s": mx.nd.array(rs.normal(0, 1, (4,)).astype("f"))}
    f = str(tmp_path / "p.params")
    mx.nd.save(f, data)
    blob = open(f, "rb").read()
    loaded = mx.nd.load_frombuffer(blob)
    assert set(loaded) == set(data)
    for k in data:
        np.testing.assert_array_equal(loaded[k].asnumpy(),
                                      data[k].asnumpy())
    # reference-era dmlc container blob too
    f2 = str(tmp_path / "ref.params")
    mx.nd.save_reference_format(f2, data)
    loaded2 = mx.nd.load_frombuffer(open(f2, "rb").read())
    for k in data:
        np.testing.assert_array_equal(loaded2[k].asnumpy(),
                                      data[k].asnumpy())


def test_predictor_bytes_blob_no_tempfile(tmp_path, monkeypatch):
    """The param blob parses IN MEMORY — the tempfile round trip is
    gone from the model-load path."""
    import tempfile

    def _boom(*a, **k):
        raise AssertionError("predictor wrote the param blob to disk")

    net = _mlp_symbol()
    params = _init_params(net, data=(2, 8))
    f = str(tmp_path / "p.params")
    mx.nd.save(f, params)
    blob = open(f, "rb").read()
    monkeypatch.setattr(tempfile, "NamedTemporaryFile", _boom)
    from mxnet_tpu.predictor import Predictor
    p = Predictor(net.tojson(), blob, {"data": (2, 8)})
    p.set_input("data", np.ones((2, 8), "f"))
    p.forward()
    assert p.get_output(0).shape == (2, 4)


def test_serving_predictor_accepts_bytes_blob(tmp_path):
    net = _mlp_symbol()
    params = _init_params(net, data=(4, 8))
    f = str(tmp_path / "p.params")
    mx.nd.save(f, params)
    pred = serving.BucketedPredictor(
        net.tojson(), open(f, "rb").read(), {"data": (4, 8)})
    out = pred.predict(np.ones((3, 8), "f"))
    assert out[0].shape == (3, 4)


def test_donated_inference_parity(monkeypatch):
    """MXNET_DONATE_INFER=1: the donated cached-op forward is numerically
    identical to the standard one, and recording-mode training still
    rides the non-donated path."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(3)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(2))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    x = mx.nd.array(np.random.RandomState(10).normal(
        0, 1, (4, 6)).astype("f"))
    monkeypatch.setenv("MXNET_DONATE_INFER", "0")
    ref = net(x).asnumpy()
    monkeypatch.setenv("MXNET_DONATE_INFER", "1")
    got = net(x).asnumpy()
    np.testing.assert_array_equal(ref, got)
    # training under the env flag: the recording path must bypass
    # donation (a donated weight/input would break the vjp replay)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01}, kvstore="tpu_sync",
                       update_on_kvstore=False)
    with autograd.record():
        loss = gluon.loss.L2Loss()(net(x), mx.nd.zeros((4, 2)))
    loss.backward()
    tr.step(4)
    assert np.isfinite(float(loss.asnumpy().ravel()[0]))


def test_donate_weights_update_parity(monkeypatch):
    """MXNET_DONATE_WEIGHTS=1 changes buffer ownership, never math: a
    3-step training run matches the non-donated run bitwise."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    def run(flag):
        monkeypatch.setenv("MXNET_DONATE_WEIGHTS", flag)
        mx.random.seed(11)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="relu"))
            net.add(nn.Dense(1))
        net.hybridize()
        net.initialize(mx.init.Xavier(), ctx=mx.cpu())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore="tpu_sync", update_on_kvstore=False)
        rs = np.random.RandomState(12)
        x = mx.nd.array(rs.normal(0, 1, (8, 6)).astype("f"))
        y = mx.nd.array(rs.normal(0, 1, (8, 1)).astype("f"))
        for _ in range(3):
            with autograd.record():
                loss = gluon.loss.L2Loss()(net(x), y)
            loss.backward()
            tr.step(8)
        return [p.data().asnumpy() for p in net.collect_params().values()]

    ref = run("0")
    got = run("1")
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)


def test_gluon_jit_cache_counters_populated():
    """snapshot()["jit_cache"] covers the gluon cached-op path: the
    first hybridized forward is a miss, repeats are hits."""
    from mxnet_tpu.gluon import nn
    mx.random.seed(4)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    x = mx.nd.ones((2, 3))
    h0, m0 = m.JIT_CACHE_HITS.value, m.JIT_CACHE_MISSES.value
    net(x)  # first call: one miss (deferred-init retry may also hit)
    assert m.JIT_CACHE_MISSES.value == m0 + 1
    h1 = m.JIT_CACHE_HITS.value
    net(x)
    net(x)
    assert m.JIT_CACHE_HITS.value == h1 + 2
    assert m.JIT_CACHE_MISSES.value == m0 + 1
    snap = obs.snapshot()["jit_cache"]
    assert snap["hits"] >= 2 and snap["misses"] >= 1


def test_serving_snapshot_and_padding_waste():
    pred, _, _ = _mlp_predictor(max_batch=8)
    pred.warmup()
    pred.predict(np.ones((6, 8), "f"))  # bucket 8 -> waste 0.25
    snap = obs.snapshot()["serving"]
    for k in ("requests", "batches", "compiles", "queue_depth",
              "padding_waste", "latency_ms_mean"):
        assert k in snap, snap
    assert abs(m.SERVE_PADDING_WASTE.get() - 0.25) < 1e-9
    assert snap["requests"] >= 1 and snap["batches"] >= 1


def test_compile_cache_dir_wires(tmp_path, monkeypatch):
    """MXNET_COMPILE_CACHE_DIR populates a persistent on-disk cache at
    serving compile time (restart-skips-compile is the product claim;
    on-disk artifacts are the observable)."""
    import jax

    import mxnet_tpu.base as base
    saved = {k: getattr(jax.config, k) for k in
             ("jax_compilation_cache_dir",
              "jax_persistent_cache_min_compile_time_secs",
              "jax_persistent_cache_min_entry_size_bytes")}
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(base, "_COMPILE_CACHE_WIRED", False)
    try:
        pred, _, _ = _mlp_predictor(max_batch=2)
        pred.warmup()
        assert base._COMPILE_CACHE_WIRED
        # jax writes cache entries asynchronously with the compile
        # itself; the wiring (config accepted) is what we pin — entry
        # files appear on backends that support serialization
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        # un-wire: jax config is process-global, and tmp_path is deleted
        # after this test — later compiles must not try to persist into
        # a dead directory
        for k, v in saved.items():
            jax.config.update(k, v)
        base._COMPILE_CACHE_WIRED = False


# -- close()/worker-death contract (ISSUE 6 satellite) ------------------------

def test_microbatcher_submit_after_close_raises_immediately():
    pred, _, _ = _mlp_predictor(max_batch=4)
    bat = serving.MicroBatcher(pred, max_wait_ms=5)
    bat.close()
    with pytest.raises(serving.BatcherClosedError, match="closed"):
        bat.submit(data=np.ones((1, 8), "f"))


def test_microbatcher_close_timeout_fails_pending_not_hang():
    """close(timeout) overrunning a hung dispatch must fail every
    queued request (including the displaced pending-slot one) with a
    typed error — callers never hang in Future.result()."""
    from mxnet_tpu import faultinject as fi
    pred, _, _ = _mlp_predictor(max_batch=4)
    pred.warmup()
    with fi.active(fi.FaultPlan().add("serving.dispatch", "delay",
                                      delay_s=0.6)):
        bat = serving.MicroBatcher(pred, max_wait_ms=0, max_batch=4)
        first = bat.submit(data=np.ones((1, 8), "f"))  # enters dispatch
        time.sleep(0.05)
        # 4-row request displaces into the pending slot; 1-row queues
        disp = bat.submit(data=np.ones((4, 8), "f"))
        tail = bat.submit(data=np.ones((1, 8), "f"))
        t0 = time.perf_counter()
        bat.close(timeout=0.05)  # join times out mid-dispatch
        assert time.perf_counter() - t0 < 0.5
        for fut in (disp, tail):
            with pytest.raises(serving.BatcherClosedError,
                               match="before dispatch"):
                fut.result(timeout=5)
        # the in-flight request still completes (or fails) on its own
        assert first.result(timeout=5)[0].shape == (1, 4)
    bat._thread.join(timeout=5)  # dispatcher exits via re-armed sentinel
    assert not bat._thread.is_alive()


# -- auto-reload hardening (ISSUE 6 satellite) --------------------------------

def test_auto_reload_survives_transient_failure_and_counts(tmp_path):
    """A transiently failing checkpoint scan must not kill the reload
    thread: failures are counted in serving reload_failures, old
    weights keep serving, and the poller recovers when storage does."""
    from mxnet_tpu import checkpoint as ckpt
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                             name="fc")
    w = np.ones((2, 8), "f")
    pred = serving.BucketedPredictor(
        net, {"arg:fc_weight": w, "arg:fc_bias": np.zeros(2, "f")},
        {"data": (2, 8)})
    x = np.ones((1, 8), "f")
    ref = pred.predict(x)[0]
    mgr = ckpt.CheckpointManager(str(tmp_path), async_save=False)
    orig, calls = mgr.latest_step, {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient checkpoint-dir scan failure")
        return orig()

    mgr.latest_step = flaky
    f0 = m.SERVE_RELOAD_FAILURES.value
    pred.start_auto_reload(mgr, interval_s=0.02)
    try:
        deadline = time.monotonic() + 10
        while m.SERVE_RELOAD_FAILURES.value < f0 + 2:
            assert time.monotonic() < deadline, "failures not counted"
            time.sleep(0.02)
        assert pred._reload_thread.is_alive(), "reload thread died"
        np.testing.assert_array_equal(pred.predict(x)[0], ref)
        assert obs.snapshot()["serving"]["reload_failures"] >= 2
        # storage recovers: the next poll picks up the new checkpoint
        mgr.save(7, {"param:fc_weight": w * 2,
                     "param:fc_bias": np.zeros(2, "f")})
        deadline = time.monotonic() + 10
        while pred.loaded_step != 7:
            assert time.monotonic() < deadline, "never reloaded"
            time.sleep(0.02)
        np.testing.assert_array_equal(pred.predict(x)[0], ref * 2)
    finally:
        pred.stop_auto_reload()
