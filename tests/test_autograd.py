"""Autograd tests (parity model: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_basic_backward():
    x = nd.array(np.random.randn(3, 4).astype("f"))
    x.attach_grad()
    with autograd.record():
        y = (x * x + 2 * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy() + 2, rtol=1e-5)


def test_chain_and_branches():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        a = x * 2
        b = a + x
        c = (b * b).sum()
    c.backward()
    # d/dx (3x)^2 = 18x
    assert_almost_equal(x.grad.asnumpy(), 18 * x.asnumpy(), rtol=1e-5)


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad.asnumpy(), 3 * 2 * x.asnumpy(), rtol=1e-5)


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 20.0]))
    assert_almost_equal(x.grad.asnumpy(), [30.0, 60.0])


def test_recording_state():
    assert not autograd.is_recording()
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        assert autograd.is_recording()
    with autograd.record(train_mode=False):
        assert autograd.is_recording() and not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training() and not autograd.is_recording()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    # z = const(4) * x → dz/dx = 4
    assert_almost_equal(x.grad.asnumpy(), [4.0])


def test_retain_graph():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(g1, [6.0])


def test_mark_variables():
    x = nd.array([1.0, 2.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x ** 3).sum()
    y.backward()
    assert_almost_equal(g.asnumpy(), 3 * x.asnumpy() ** 2, rtol=1e-5)


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array(np.random.randn(5).astype("f"))
    x.attach_grad()
    fn = Sigmoid()
    with autograd.record():
        y = fn(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad.asnumpy(), s * (1 - s), rtol=1e-4, atol=1e-5)


def test_grad_function():
    x = nd.array([1.0, 2.0])
    with autograd.record():
        y = (x * x).sum()
    grads = autograd.grad([y], [x])
    assert_almost_equal(grads[0].asnumpy(), 2 * x.asnumpy())


def test_mutation_after_record():
    # gradient uses the value at record time, not after mutation
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    x += 100  # mutate after recording
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [4.0])


def test_dropout_identity_grad():
    x = nd.ones((10, 10))
    x.attach_grad()
    with autograd.record(train_mode=False):
        y = nd.Dropout(x, p=0.5)
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), np.ones((10, 10)))


def test_tape_key_recycling_stress():
    """Gradients stay correct when many intermediate NDArrays are garbage
    collected mid-record (CPython id reuse must not alias tape keys)."""
    import gc
    x = nd.array(np.ones((4, 4), "f"))
    x.attach_grad()
    with autograd.record():
        acc = x * 1.0
        for i in range(50):
            tmp = acc * 2.0
            acc = tmp * 0.5 + x * 0.0
            del tmp
            if i % 7 == 0:
                gc.collect()
        loss = acc.sum()
    loss.backward()
    assert_almost_equal(x.grad.asnumpy(), np.ones((4, 4), "f"),
                        rtol=1e-5, atol=1e-6)


def test_view_ops_recorded():
    """reshape/transpose/slice participate in the tape."""
    x = nd.array(np.arange(12, dtype="f").reshape(3, 4))
    x.attach_grad()
    with autograd.record():
        y = x.reshape((4, 3)).transpose()
        loss = (y[0:2] * 2).sum() + x[1].sum() + x[:, 0:2].sum()
    loss.backward()
    import jax
    import jax.numpy as jnp

    def f(a):
        yy = jnp.transpose(a.reshape(4, 3))
        return (yy[0:2] * 2).sum() + a[1].sum() + a[:, 0:2].sum()

    g_ref = np.asarray(jax.grad(f)(jnp.asarray(x.asnumpy())))
    assert_almost_equal(x.grad.asnumpy(), g_ref, rtol=1e-5, atol=1e-6)
