"""Sharded sparse embeddings (ISSUE 20, mxnet_tpu/embedding/ +
docs/embedding.md).

Contracts pinned here:
  * a sparse-embedding + dense-tower net is WHOLE-STEP ELIGIBLE: it
    trains at <=2 steady-state XLA dispatches per step (expect 1 — the
    lookup, the row-sparse grad segment-sum, and the ``.at[ids]``
    scatter update all ride the donated program);
  * f32 whole-step training is BITWISE identical to the fused sparse
    path (eager backward -> allreduce_rowsparse -> update_sparse) over
    5 steps — both paths share clip-before-record ids, the
    unique + ``.at[inv].add`` segment-sum, the same per-row fused_step
    and the same scatter-back;
  * ``audit_programs``/the program_audit fixture confirm the embedding
    table is REALLY aliased — donation survived the in-program scatter;
  * a K=4 superstep carries the sparse state bitwise vs sequential
    whole steps;
  * ``ShardedEmbedding`` tables register under their own HBM-ledger
    tag ``embed_shards`` and pin row partitioning over the mesh
    ``model`` axis (``MXNET_EMBED_SHARD_AXIS``).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.autotune.superstep import SuperStepCompiler
from mxnet_tpu.embedding import ShardedEmbedding, row_partition_spec
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.wholestep import WholeStepCompiler
from mxnet_tpu.observability import memory
from mxnet_tpu.observability import metrics as M

VOCAB, DIM, FEATS, BATCH = 50, 8, 4, 8


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("MXNET_AMP", raising=False)
    monkeypatch.delenv("MXNET_SUPERSTEP_K", raising=False)
    monkeypatch.delenv("MXNET_EMBED_SHARD_AXIS", raising=False)
    monkeypatch.delenv("MXNET_EMBED_DEDUP_IDS", raising=False)
    yield


def _net(seed=2, sharded=False):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        if sharded:
            net.add(ShardedEmbedding(VOCAB, DIM))
        else:
            net.add(nn.Embedding(VOCAB, DIM, sparse_grad=True))
        net.add(nn.Flatten())
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(1))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    return net


def _trainer(net, opt="sgd", opt_params=None):
    return gluon.Trainer(
        net.collect_params(), opt,
        opt_params or {"learning_rate": 0.05, "momentum": 0.9},
        kvstore="tpu_sync", update_on_kvstore=False)


def _batches(n, seed=0):
    rs = np.random.RandomState(seed)
    return [(mx.nd.array(rs.randint(0, VOCAB, (BATCH, FEATS)).astype("f")),
             mx.nd.array(rs.normal(0, 1, (BATCH, 1)).astype("f")))
            for _ in range(n)]


def _weights(net):
    return [p.data().asnumpy().astype("f")
            for p in net.collect_params().values()]


def _run(monkeypatch, whole, steps=5, opt="sgd", opt_params=None,
         sharded=False, seed=2):
    monkeypatch.setenv("MXNET_WHOLE_STEP", "1" if whole else "0")
    net = _net(seed, sharded=sharded)
    tr = _trainer(net, opt, opt_params)
    st = WholeStepCompiler(net, gluon.loss.L2Loss(), tr)
    losses = [float(st.step(x, y).asnumpy().mean())
              for x, y in _batches(steps)]
    return losses, _weights(net), tr, st


# ---------------------------------------------------------------------------
# numerics: whole-step bitwise vs the fused sparse path
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opt,opt_params", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 3e-3}),
])
def test_sparse_wholestep_f32_bitwise_matches_fused(monkeypatch, opt,
                                                    opt_params):
    lw, ww, _, st = _run(monkeypatch, True, opt=opt, opt_params=opt_params)
    assert st.active, st.fallback_reason
    lf, wf, _, _ = _run(monkeypatch, False, opt=opt, opt_params=opt_params)
    np.testing.assert_array_equal(lw, lf)
    for a, b in zip(ww, wf):
        np.testing.assert_array_equal(a, b)


def test_sparse_wholestep_sharded_block_bitwise(monkeypatch):
    """ShardedEmbedding is numerically the parent block: the mesh spec
    hook and ledger tag must not change a single bit of training."""
    ls, ws, _, st = _run(monkeypatch, True, sharded=True)
    assert st.active, st.fallback_reason
    lp, wp, _, _ = _run(monkeypatch, True, sharded=False)
    np.testing.assert_array_equal(ls, lp)
    for a, b in zip(ws, wp):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# perf gate: <=2 dispatches/step + donation really aliased
# ---------------------------------------------------------------------------
@pytest.mark.perf_smoke
def test_sparse_wholestep_dispatch_budget(monkeypatch):
    monkeypatch.setenv("MXNET_WHOLE_STEP", "1")
    net = _net()
    tr = _trainer(net)
    st = WholeStepCompiler(net, gluon.loss.L2Loss(), tr)
    batches = _batches(6)
    for x, y in batches[:2]:  # compile + warmup
        st.step(x, y)
    assert st.active, st.fallback_reason
    per_step = []
    for x, y in batches[2:]:
        d0 = M.step_dispatches()
        st.step(x, y)
        per_step.append(M.step_dispatches() - d0)
    assert all(d <= 2 for d in per_step), per_step
    assert any(d == 1 for d in per_step), per_step


@pytest.mark.program_audit
def test_embedding_donation_survives_scatter(monkeypatch, program_audit):
    """The acceptance pin: the table flows through the in-program
    ``.at[uids].set`` scatter and still comes out INPUT-OUTPUT aliased
    (a dropped alias would silently double the table's HBM)."""
    lw, _, _, st = _run(monkeypatch, True, steps=3)
    assert st.active, st.fallback_reason
    aliased = program_audit("whole_step", min_aliased=1)
    assert len(aliased) >= 1, aliased


# ---------------------------------------------------------------------------
# superstep: K=4 carries the sparse state bitwise
# ---------------------------------------------------------------------------
def test_superstep_k4_carries_sparse_state_bitwise(monkeypatch):
    monkeypatch.setenv("MXNET_WHOLE_STEP", "1")
    K, groups = 4, 2
    batches = _batches(K * groups)

    net_s = _net()
    net_s(batches[0][0])  # materialize shapes so the FIRST group scans
    st_s = SuperStepCompiler(net_s, gluon.loss.L2Loss(), _trainer(net_s))
    super_losses = []
    for g in range(groups):
        xs = [b[0] for b in batches[g * K:(g + 1) * K]]
        ys = [b[1] for b in batches[g * K:(g + 1) * K]]
        super_losses.append(st_s.superstep(xs, ys).asnumpy())
        assert st_s.super_active, st_s.fallback_reason

    net_q = _net()
    net_q(batches[0][0])
    st_q = WholeStepCompiler(net_q, gluon.loss.L2Loss(), _trainer(net_q))
    seq = [st_q.step(x, y).asnumpy() for x, y in batches]
    assert st_q.active, st_q.fallback_reason

    np.testing.assert_array_equal(
        np.concatenate(super_losses, axis=0), np.stack(seq))
    for a, b in zip(_weights(net_s), _weights(net_q)):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# ShardedEmbedding hooks: ledger tag + partition spec
# ---------------------------------------------------------------------------
@pytest.mark.memory
def test_embed_shards_ledger_tag(monkeypatch):
    if not memory.ENABLED:
        pytest.skip("memory ledger disabled")
    lw, _, _, st = _run(monkeypatch, True, steps=2, sharded=True)
    assert st.active, st.fallback_reason
    tags = memory.report().get("device", {}).get("tags", {})
    assert tags.get("embed_shards", {}).get("live_bytes", 0) > 0, tags


def test_row_partition_spec_follows_env(monkeypatch):
    from jax.sharding import PartitionSpec
    from mxnet_tpu.parallel import mesh as pmesh
    mesh = pmesh.make_mesh(batch=4, model=2)
    assert row_partition_spec(mesh) == PartitionSpec("model", None)
    monkeypatch.setenv("MXNET_EMBED_SHARD_AXIS", "batch")
    assert row_partition_spec(mesh) == PartitionSpec("batch", None)
    monkeypatch.setenv("MXNET_EMBED_SHARD_AXIS", "nope")
    assert row_partition_spec(mesh) == PartitionSpec()  # replicate
    emb = ShardedEmbedding(VOCAB, DIM)
    monkeypatch.delenv("MXNET_EMBED_SHARD_AXIS")
    plan = emb.partition_plan(mesh)
    assert plan["axis"] == "model" and plan["shards"] == 2
    assert plan["rows_per_shard"] == VOCAB // 2 + (VOCAB % 2 > 0)
    ids = mx.nd.array(np.array([[1, 1, 2], [3, 3, 3]], dtype="f"))
    assert emb.wire_rows(ids) == 3  # unique rows, not batch tokens


def test_dedup_ids_env_keeps_numerics(monkeypatch):
    """MXNET_EMBED_DEDUP_IDS=0 ships raw concatenated (ids, rows) over
    the wire and defers the segment-sum to update_sparse's in-program
    unique — training must be numerically unchanged (same rows summed,
    one place later)."""
    l1, w1, _, _ = _run(monkeypatch, False)
    monkeypatch.setenv("MXNET_EMBED_DEDUP_IDS", "0")
    l0, w0, _, _ = _run(monkeypatch, False)
    np.testing.assert_array_equal(l1, l0)
    for a, b in zip(w1, w0):
        np.testing.assert_array_equal(a, b)
