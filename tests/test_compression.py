"""2-bit error-feedback gradient compression — the bucket-level
programs and the compressed bucketed allreduce (ISSUE 3 tentpole).

Reference semantics (src/kvstore/gradient_compression.h:37-134):
r = grad + residual; r >= +T maps to +T, r <= -T to -T, else 0; the
residual keeps r - out so the quantization error feeds the next step.
The map is purely elementwise, so flat per-bucket residual buffers
preserve per-parameter error feedback exactly — pinned here against a
numpy reference and against the per-key quantizer; the Gluon
fused-vs-legacy training parity lives in tests/test_fused_step.py.
"""
import pickle

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.kvstore import (_compressed_reduce_local, _dequantize_sum,
                               _quantize_buckets)
from mxnet_tpu.observability import metrics as M


def _ref_quantize(grad, residual, threshold):
    """Numpy reference of the reference threshold map (the kernel in
    gradient_compression-inl.h)."""
    r = grad.astype("f") + residual
    out = np.where(r >= threshold, threshold,
                   np.where(r <= -threshold, -threshold, 0.0)).astype("f")
    return out, (r - out).astype("f")


# ------------------------------------------------ bucket-level programs

def test_bucket_quantize_matches_reference_threshold_map():
    """+T / -T / 0 cells and the residual update, over multiple rounds
    so the error feedback carries across calls like a training loop."""
    rs = np.random.RandomState(0)
    thr = 0.5
    flats = [rs.normal(0, 0.7, (37,)).astype("f"),
             rs.normal(0, 0.7, (8,)).astype("f")]
    flats[0][:4] = [thr, -thr, thr - 0.01, -thr + 0.01]  # boundary cells
    res = [np.zeros(37, "f"), np.zeros(8, "f")]
    for _ in range(3):
        outs, new_res, _ = _compressed_reduce_local(
            [jnp.asarray(f) for f in flats],
            [jnp.asarray(r) for r in res], thr)
        for j in range(2):
            exp, exp_res = _ref_quantize(flats[j], res[j], thr)
            np.testing.assert_allclose(np.asarray(outs[j]), exp, rtol=1e-6)
            np.testing.assert_allclose(np.asarray(new_res[j]), exp_res,
                                       rtol=1e-6, atol=1e-7)
            assert set(np.unique(np.asarray(outs[j]))) <= {0.0, thr, -thr}
            res[j] = np.asarray(new_res[j])
        flats = [rs.normal(0, 0.7, f.shape).astype("f") for f in flats]


def test_packing_density():
    """<= ceil(n/4) payload bytes per bucket — 4 codes/byte, including
    the padded tail when n is not a multiple of 4."""
    for n in (1, 2, 3, 4, 5, 37, 128):
        packed, _, _ = _quantize_buckets(
            [jnp.ones((n,), jnp.float32)],
            [jnp.zeros(n, jnp.float32)], 0.5)
        assert str(packed[0].dtype) == "uint8", packed[0].dtype
        assert packed[0].nbytes <= (n + 3) // 4, (n, packed[0].nbytes)


def test_dequantize_sum_over_worker_stack():
    """The dist-leg half: each worker's packed payload dequantizes
    independently and the results sum (the reference's server-side
    dequantize-and-aggregate)."""
    rs = np.random.RandomState(1)
    thr = 0.5
    g1 = rs.normal(0, 1, (11,)).astype("f")
    g2 = rs.normal(0, 1, (11,)).astype("f")
    z = lambda: [jnp.zeros(11, jnp.float32)]  # noqa: E731
    p1, _, _ = _quantize_buckets([jnp.asarray(g1)], z(), thr)
    p2, _, _ = _quantize_buckets([jnp.asarray(g2)], z(), thr)
    out = _dequantize_sum([jnp.stack([p1[0], p2[0]])], thr,
                          ((11,),), ("float32",))
    e1, _ = _ref_quantize(g1, np.zeros(11, "f"), thr)
    e2, _ = _ref_quantize(g2, np.zeros(11, "f"), thr)
    np.testing.assert_allclose(np.asarray(out[0]), e1 + e2, rtol=1e-6)


# ------------------------------------------- KVStore.allreduce variant

def test_compressed_allreduce_threshold_plumbing_and_wire_bytes():
    """Threshold parameter reaches the bucket programs (outputs live in
    {+T, -T, 0}), shapes round-trip, and the KVSTORE_WIRE_BYTES gauges
    report the 2-bit payload: compressed <= raw/8 (ISSUE 3 acceptance;
    actual ratio is 1/16 + padding)."""
    kv = mx.kv.create("tpu_sync")
    thr = 2.0
    rs = np.random.RandomState(2)
    vals = [mx.nd.array(rs.normal(0, 3, (9, 5)).astype("f")),
            mx.nd.array(rs.normal(0, 3, (17,)).astype("f"))]
    reduced, res = kv.allreduce(
        vals, compression={"type": "2bit", "threshold": thr})
    assert [r.shape for r in reduced] == [(9, 5), (17,)]
    for r in reduced:
        u = set(np.unique(r.asnumpy()))
        assert u <= {0.0, thr, -thr}, u
    assert len(res) == 2 and res[0].shape == (45,) and res[1].shape == (17,)
    raw = M.KVSTORE_WIRE_BYTES.get(leg="dist", stage="raw")
    packed = M.KVSTORE_WIRE_BYTES.get(leg="dist", stage="compressed")
    assert raw == 4 * (45 + 17), raw
    assert packed == (45 + 3) // 4 + (17 + 3) // 4, packed
    assert packed * 8 <= raw
    assert M.KVSTORE_WIRE_BYTES.get(leg="intra", stage="raw") == raw


def test_compressed_allreduce_error_feedback_round_trip():
    """Residuals returned by one call feed the next: a gradient below
    threshold accumulates until it crosses it (the error-feedback
    contract that makes 2-bit training converge)."""
    kv = mx.kv.create("tpu_sync")
    comp = {"type": "2bit", "threshold": 0.5}
    g = mx.nd.array(np.full(6, 0.2, "f"))
    # r accumulates 0.2/step: 0.2 -> 0, 0.4 -> 0, 0.6 >= T -> +T
    res = None
    for expect in (0.0, 0.0, 0.5):
        out, res = kv.allreduce([g], compression=comp, residuals=res)
        np.testing.assert_allclose(out[0].asnumpy(), np.full(6, expect),
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(res[0]), np.full(6, 0.1, "f"),
                               rtol=1e-5)  # 0.6 - 0.5 carries forward


def test_bucket_residuals_equal_per_key_residuals():
    """Concatenated per-key quantization == flat-bucket quantization —
    the elementwise invariant that lets compression compose with
    bucketing without changing error-feedback semantics."""
    thr = 0.5
    kv = mx.kv.create("tpu_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": thr})
    rs = np.random.RandomState(3)
    shapes = [(4, 3), (7,), (5,)]
    grads = [rs.normal(0, 0.6, s).astype("f") for s in shapes]
    for _ in range(2):  # two rounds so per-key residuals are non-zero
        per_key = [kv._compress(i, mx.nd.array(g))
                   for i, g in enumerate(grads)]
    flat = np.concatenate([g.ravel() for g in grads])
    kv2 = mx.kv.create("tpu_sync")
    res = None
    for _ in range(2):
        reduced, res = kv2.allreduce(
            [mx.nd.array(flat)],
            compression={"type": "2bit", "threshold": thr}, residuals=res)
    np.testing.assert_allclose(
        reduced[0].asnumpy(),
        np.concatenate([p.asnumpy().ravel() for p in per_key]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(res[0]),
        np.concatenate([np.asarray(kv._residuals[i]) for i in range(3)]),
        rtol=1e-6, atol=1e-7)


def test_compression_error_metric_and_knob(monkeypatch):
    """The compression_error histogram observes one mean-|error| sample
    per bucket; MXNET_COMPRESSION_ERROR_METRIC=0 skips the device sync."""
    kv = mx.kv.create("tpu_sync")
    comp = {"type": "2bit", "threshold": 0.5}
    monkeypatch.setenv("MXNET_COMPRESSION_ERROR_METRIC", "0")
    c0 = M.COMPRESSION_ERROR.count
    kv.allreduce([mx.nd.array(np.full(8, 0.2, "f"))], compression=comp)
    assert M.COMPRESSION_ERROR.count == c0
    monkeypatch.delenv("MXNET_COMPRESSION_ERROR_METRIC", raising=False)
    kv.allreduce([mx.nd.array(np.full(8, 0.2, "f"))], compression=comp)
    assert M.COMPRESSION_ERROR.count == c0 + 1
    # 0.2 below threshold -> everything is error
    assert M.COMPRESSION_ERROR.sum > 0


# ---------------------------------------- Trainer residual checkpoints

def _mlp(depth=4, width=8, seed=11):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        for _ in range(depth):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(1))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    return net


_COMP = {"type": "2bit", "threshold": 0.5}


def _trainer(net, comp=_COMP):
    return gluon.Trainer(net.collect_params(), "sgd",
                         {"learning_rate": 0.05, "momentum": 0.9},
                         kvstore="tpu_sync", update_on_kvstore=False,
                         compression_params=comp)


def _batch():
    rs = np.random.RandomState(0)
    return (mx.nd.array(rs.normal(0, 1, (8, 16)).astype("f")),
            mx.nd.array(rs.normal(0, 1, (8, 1)).astype("f")))


def _step(net, tr, x, y, loss_fn):
    with autograd.record():
        l = loss_fn(net(x), y)
    l.backward()
    tr.step(8)


def test_trainer_threshold_plumbing():
    net = _mlp()
    tr = _trainer(net, comp={"type": "2bit", "threshold": 2.0})
    x, y = _batch()
    _step(net, tr, x, y, gluon.loss.L2Loss())
    assert tr._kv._gc.threshold == 2.0
    assert tr._residuals is not None  # fused-compressed path engaged


def test_residuals_survive_checkpoint(tmp_path):
    """save_states/load_states round-trips the error-feedback state:
    resume == continuous training, bit-for-bit on weights AND
    residuals (a silent zero-reset would diverge within one step)."""
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net1 = _mlp()
    t1 = _trainer(net1)
    for _ in range(5):
        _step(net1, t1, x, y, loss_fn)
    fname = str(tmp_path / "trainer.states")
    t1.save_states(fname)
    snap = [p.data().asnumpy().copy()
            for p in net1.collect_params().values()]
    for _ in range(2):
        _step(net1, t1, x, y, loss_fn)
    ref_w = [p.data().asnumpy() for p in net1.collect_params().values()]
    ref_res = [np.asarray(r) for r in t1._residuals]

    net2 = _mlp(seed=99)  # different init — weights restored from snap
    for p, w in zip(net2.collect_params().values(), snap):
        p.set_data(mx.nd.array(w))
    t2 = _trainer(net2)
    t2.load_states(fname)
    for _ in range(2):
        _step(net2, t2, x, y, loss_fn)
    for a, b in zip(ref_w,
                    [p.data().asnumpy()
                     for p in net2.collect_params().values()]):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-8)
    for a, b in zip(ref_res, [np.asarray(r) for r in t2._residuals]):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-8)


def test_residual_signature_mismatch_raises(tmp_path):
    """Loading residuals saved for a different model must raise clearly
    — both when the target trainer has already stepped (immediate) and
    when it has not (at first bucketer build)."""
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net1 = _mlp(depth=4)
    t1 = _trainer(net1)
    for _ in range(3):
        _step(net1, t1, x, y, loss_fn)
    fname = str(tmp_path / "trainer.states")
    t1.save_states(fname)

    net2 = _mlp(depth=5)
    net2(x)  # materialize deferred shapes
    t2 = _trainer(net2)
    t2.load_states(fname)  # not stepped yet: deferred check
    with pytest.raises(MXNetError, match="residuals"):
        _step(net2, t2, x, y, loss_fn)

    net3 = _mlp(depth=5)
    t3 = _trainer(net3)
    for _ in range(2):
        _step(net3, t3, x, y, loss_fn)
    with pytest.raises(MXNetError, match="residuals"):
        t3.load_states(fname)  # already stepped: immediate check


def test_residual_bucket_cap_mismatch_raises(tmp_path, monkeypatch):
    """Same params, different MXNET_BUCKET_SIZE_MB: the param signature
    matches but the residual bucket layout does not — must raise the
    same clear error, not die on shapes inside the jitted quantize."""
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net1 = _mlp()
    t1 = _trainer(net1)
    for _ in range(3):
        _step(net1, t1, x, y, loss_fn)
    assert len(t1._residuals) == 1  # default cap: one bucket
    fname = str(tmp_path / "trainer.states")
    t1.save_states(fname)

    monkeypatch.setenv("MXNET_BUCKET_SIZE_MB", "0.0001")  # bucket/param
    net2 = _mlp(seed=99)
    net2(x)
    t2 = _trainer(net2)
    t2.load_states(fname)
    with pytest.raises(MXNetError, match="residuals"):
        _step(net2, t2, x, y, loss_fn)


def test_uncompressed_state_format_unchanged(tmp_path):
    """Without compression the file stays the raw updater-state pickle
    (no sentinel wrapper) so pre-existing checkpoints keep loading."""
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net = _mlp()
    tr = _trainer(net, comp=None)
    for _ in range(2):
        _step(net, tr, x, y, loss_fn)
    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)
    with open(fname, "rb") as f:
        obj = pickle.loads(f.read())
    assert not (isinstance(obj, dict)
                and obj.get("__mxt_trainer_states__"))
    tr.load_states(fname)  # raw format loads
