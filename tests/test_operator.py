"""Operator tests (parity model: tests/python/unittest/test_operator.py —
numpy-reference forward checks + finite-difference gradient checks)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward,
                                  check_symbolic_backward)


def test_unary_ops_vs_numpy():
    x = np.random.uniform(0.5, 2.0, (3, 4)).astype("f")
    a = nd.array(x)
    cases = {
        "relu": np.maximum(x, 0), "sigmoid": 1 / (1 + np.exp(-x)),
        "exp": np.exp(x), "log": np.log(x), "sqrt": np.sqrt(x),
        "square": x * x, "abs": np.abs(x), "tanh": np.tanh(x),
        "floor": np.floor(x), "ceil": np.ceil(x), "sign": np.sign(x),
        "rsqrt": 1 / np.sqrt(x), "log1p": np.log1p(x),
        "expm1": np.expm1(x), "sin": np.sin(x), "cos": np.cos(x),
        "arctan": np.arctan(x), "sinh": np.sinh(x),
    }
    for name, expect in cases.items():
        out = getattr(nd, name)(a)
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-4, atol=1e-5)


def test_binary_broadcast():
    x = np.random.rand(2, 3, 1).astype("f") + 0.5
    y = np.random.rand(1, 3, 4).astype("f") + 0.5
    a, b = nd.array(x), nd.array(y)
    assert_almost_equal(nd.broadcast_add(a, b).asnumpy(), x + y, rtol=1e-5)
    assert_almost_equal(nd.broadcast_mul(a, b).asnumpy(), x * y, rtol=1e-5)
    assert_almost_equal(nd.broadcast_div(a, b).asnumpy(), x / y, rtol=1e-4)
    assert_almost_equal(nd.broadcast_power(a, b).asnumpy(), x ** y, rtol=1e-4)
    assert_almost_equal(nd.broadcast_hypot(a, b).asnumpy(), np.hypot(x, y),
                        rtol=1e-4)


def test_reductions():
    x = np.random.randn(2, 3, 4).astype("f")
    a = nd.array(x)
    assert_almost_equal(nd.sum(a, axis=1).asnumpy(), x.sum(1), rtol=1e-5)
    assert_almost_equal(nd.sum(a, axis=(0, 2)).asnumpy(), x.sum((0, 2)),
                        rtol=1e-4)
    assert_almost_equal(nd.sum(a, axis=1, keepdims=True).asnumpy(),
                        x.sum(1, keepdims=True), rtol=1e-5)
    assert_almost_equal(nd.sum(a, axis=1, exclude=True).asnumpy(),
                        x.sum((0, 2)), rtol=1e-4)
    assert_almost_equal(nd.mean(a).asnumpy(), x.mean(), rtol=1e-5)
    assert_almost_equal(nd.prod(a, axis=2).asnumpy(), x.prod(2), rtol=1e-4)
    assert_almost_equal(nd.norm(a).asnumpy(), np.linalg.norm(x.ravel()),
                        rtol=1e-5)
    assert_almost_equal(nd.argmax(a, axis=1).asnumpy(), x.argmax(1))
    assert_almost_equal(nd.argmin(a, axis=2).asnumpy(), x.argmin(2))


def test_dot():
    x = np.random.randn(4, 5).astype("f")
    y = np.random.randn(5, 3).astype("f")
    assert_almost_equal(nd.dot(nd.array(x), nd.array(y)).asnumpy(), x @ y,
                        rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(x), nd.array(y.T), transpose_b=True).asnumpy(),
        x @ y, rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(x.T), nd.array(y), transpose_a=True).asnumpy(),
        x @ y, rtol=1e-4)
    bx = np.random.randn(2, 4, 5).astype("f")
    by = np.random.randn(2, 5, 3).astype("f")
    assert_almost_equal(nd.batch_dot(nd.array(bx), nd.array(by)).asnumpy(),
                        bx @ by, rtol=1e-4)


def test_matrix_ops():
    x = np.arange(24).reshape(2, 3, 4).astype("f")
    a = nd.array(x)
    assert_almost_equal(nd.transpose(a, axes=(2, 0, 1)).asnumpy(),
                        x.transpose(2, 0, 1))
    assert_almost_equal(nd.swapaxes(a, dim1=0, dim2=2).asnumpy(),
                        x.swapaxes(0, 2))
    assert_almost_equal(nd.flip(a, axis=1).asnumpy(), x[:, ::-1])
    assert_almost_equal(nd.tile(a, reps=(1, 2, 1)).asnumpy(),
                        np.tile(x, (1, 2, 1)))
    assert_almost_equal(nd.repeat(a, repeats=2, axis=1).asnumpy(),
                        np.repeat(x, 2, 1))
    assert_almost_equal(
        nd.slice(a, begin=(0, 1, 0), end=(2, 3, 4), step=(1, 1, 2)).asnumpy(),
        x[0:2, 1:3, 0:4:2])
    assert_almost_equal(nd.slice_axis(a, axis=2, begin=1, end=3).asnumpy(),
                        x[:, :, 1:3])
    assert_almost_equal(nd.reverse(a, axis=(0,)).asnumpy(), x[::-1])
    assert_almost_equal(
        nd.pad(nd.array(x[None]), mode="constant",
               pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=1).asnumpy(),
        np.pad(x[None], ((0, 0), (0, 0), (1, 1), (2, 2)), constant_values=1))


def test_where_take_onehot_pick():
    cond = nd.array([[1.0, 0.0], [0.0, 1.0]])
    a = nd.ones((2, 2))
    b = nd.zeros((2, 2))
    assert_almost_equal(nd.where(cond, a, b).asnumpy(), np.eye(2))
    w = np.random.randn(10, 4).astype("f")
    idx = np.array([1, 3, 5])
    assert_almost_equal(nd.take(nd.array(w), nd.array(idx)).asnumpy(), w[idx])
    assert_almost_equal(
        nd.Embedding(nd.array(idx), nd.array(w), input_dim=10,
                     output_dim=4).asnumpy(), w[idx])
    oh = nd.one_hot(nd.array([0, 2]), depth=3).asnumpy()
    assert_almost_equal(oh, np.eye(3)[[0, 2]])
    data = np.random.randn(3, 4).astype("f")
    pk = nd.pick(nd.array(data), nd.array([0, 1, 2]), axis=1).asnumpy()
    assert_almost_equal(pk, data[np.arange(3), [0, 1, 2]])


def test_ordering():
    x = np.random.randn(4, 5).astype("f")
    a = nd.array(x)
    assert_almost_equal(nd.sort(a, axis=1).asnumpy(), np.sort(x, 1))
    assert_almost_equal(nd.argsort(a, axis=1).asnumpy(), np.argsort(x, 1))
    tk = nd.topk(a, axis=1, k=2, ret_typ="value").asnumpy()
    expect = -np.sort(-x, axis=1)[:, :2]
    assert_almost_equal(tk, expect)


def test_fully_connected():
    x = np.random.randn(4, 10).astype("f")
    w = np.random.randn(6, 10).astype("f")
    b = np.random.randn(6).astype("f")
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=6)
    assert_almost_equal(out.asnumpy(), x @ w.T + b, rtol=1e-4)
    out2 = nd.FullyConnected(nd.array(x), nd.array(w), no_bias=True,
                             num_hidden=6)
    assert_almost_equal(out2.asnumpy(), x @ w.T, rtol=1e-4)


def test_convolution_vs_naive():
    x = np.random.randn(2, 3, 7, 7).astype("f")
    w = np.random.randn(4, 3, 3, 3).astype("f")
    b = np.zeros(4, "f")
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=4, stride=(2, 2),
                         pad=(1, 1)).asnumpy()
    # naive reference
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    expect = np.zeros_like(out)
    for n in range(2):
        for f in range(4):
            for i in range(out.shape[2]):
                for j in range(out.shape[3]):
                    patch = xp[n, :, i * 2:i * 2 + 3, j * 2:j * 2 + 3]
                    expect[n, f, i, j] = (patch * w[f]).sum()
    assert_almost_equal(out, expect, rtol=1e-3, atol=1e-4)


def test_pooling():
    x = np.random.randn(1, 2, 6, 6).astype("f")
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max").asnumpy()
    expect = x.reshape(1, 2, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(out, expect)
    out_avg = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                         pool_type="avg").asnumpy()
    expect_avg = x.reshape(1, 2, 3, 2, 3, 2).mean(axis=(3, 5))
    assert_almost_equal(out_avg, expect_avg, rtol=1e-5)
    gmax = nd.Pooling(nd.array(x), global_pool=True, pool_type="max").asnumpy()
    assert_almost_equal(gmax, x.max(axis=(2, 3), keepdims=True))


def test_batchnorm_train_and_infer():
    x = np.random.randn(8, 3, 4, 4).astype("f")
    gamma, beta = np.ones(3, "f"), np.zeros(3, "f")
    mm, mv = np.zeros(3, "f"), np.ones(3, "f")
    mm_nd, mv_nd = nd.array(mm), nd.array(mv)
    with mx.autograd.train_mode():
        outs = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                            mm_nd, mv_nd, fix_gamma=False, momentum=0.9)
    out = outs[0].asnumpy()
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    expect = (x - mean[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + 1e-3)
    assert_almost_equal(out, expect, rtol=1e-2, atol=1e-3)
    # moving stats updated in place
    assert_almost_equal(mm_nd.asnumpy(), 0.9 * mm + 0.1 * mean, rtol=1e-4,
                        atol=1e-5)
    assert_almost_equal(mv_nd.asnumpy(), 0.9 * mv + 0.1 * var, rtol=1e-4,
                        atol=1e-5)
    # inference mode uses moving stats
    outs_inf = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                            mm_nd, mv_nd, fix_gamma=False)
    expect_inf = (x - mm_nd.asnumpy()[None, :, None, None]) / np.sqrt(
        mv_nd.asnumpy()[None, :, None, None] + 1e-3)
    assert_almost_equal(outs_inf[0].asnumpy(), expect_inf, rtol=1e-2,
                        atol=1e-3)


def test_softmax_family():
    x = np.random.randn(4, 5).astype("f")
    sm = nd.softmax(nd.array(x)).asnumpy()
    e = np.exp(x - x.max(1, keepdims=True))
    assert_almost_equal(sm, e / e.sum(1, keepdims=True), rtol=1e-5)
    lsm = nd.log_softmax(nd.array(x)).asnumpy()
    assert_almost_equal(lsm, np.log(e / e.sum(1, keepdims=True)), rtol=1e-4,
                        atol=1e-5)


def test_softmax_output_gradient():
    # SoftmaxOutput backward = (p - onehot) * grad_scale (reference semantics)
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    out = sym.SoftmaxOutput(data, label, grad_scale=2.0)
    x = np.random.randn(4, 3).astype("f")
    y = np.array([0, 1, 2, 1], "f")
    exe = out.bind(mx.cpu(), {"data": nd.array(x), "softmax_label": nd.array(y)},
                   args_grad={"data": nd.zeros((4, 3))},
                   grad_req={"data": "write", "softmax_label": "null"})
    exe.forward(is_train=True)
    exe.backward()
    p = exe.outputs[0].asnumpy()
    onehot = np.eye(3)[y.astype(int)]
    assert_almost_equal(exe.grad_dict["data"].asnumpy(), (p - onehot) * 2.0,
                        rtol=1e-4, atol=1e-5)


def test_regression_outputs():
    data = sym.Variable("data")
    label = sym.Variable("label")
    out = sym.LinearRegressionOutput(data, label)
    x = np.random.randn(4, 3).astype("f")
    y = np.random.randn(4, 3).astype("f")
    exe = out.bind(mx.cpu(), {"data": nd.array(x), "label": nd.array(y)},
                   args_grad={"data": nd.zeros((4, 3))},
                   grad_req={"data": "write", "label": "null"})
    exe.forward(is_train=True)
    assert_almost_equal(exe.outputs[0].asnumpy(), x)
    exe.backward()
    # grad = (out - label) * grad_scale / num_output  (regression_output-inl.h:95)
    assert_almost_equal(exe.grad_dict["data"].asnumpy(), (x - y) / 3.0,
                        rtol=1e-4, atol=1e-5)


def test_numeric_gradient_fc():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=4)
    check_numeric_gradient(
        fc, {"data": np.random.randn(3, 5).astype("f"),
             "fc_weight": np.random.randn(4, 5).astype("f"),
             "fc_bias": np.random.randn(4).astype("f")},
        numeric_eps=1e-3, rtol=5e-2, atol=1e-2)


def test_numeric_gradient_elemwise():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = a * b + sym.tanh(a)
    check_numeric_gradient(
        out, {"a": np.random.rand(3, 3).astype("f") + 0.5,
              "b": np.random.rand(3, 3).astype("f") + 0.5},
        numeric_eps=1e-3, rtol=5e-2, atol=1e-2)


def test_symbolic_forward_backward_helpers():
    a = sym.Variable("a")
    out = sym.square(a)
    x = np.random.rand(3, 2).astype("f")
    check_symbolic_forward(out, {"a": x}, [x ** 2])
    check_symbolic_backward(out, {"a": x}, [np.ones_like(x)], [2 * x],
                            rtol=1e-4, atol=1e-5)
    # grad_req='add' semantics
    check_symbolic_backward(out, {"a": x}, [np.ones_like(x)], [2 * x],
                            grad_req="add", rtol=1e-4, atol=1e-5)


def test_sequence_ops():
    x = np.random.randn(4, 3, 2).astype("f")  # (seq, batch, feat)
    lens = np.array([2, 4, 1], "f")
    last = nd.SequenceLast(nd.array(x), nd.array(lens),
                           use_sequence_length=True).asnumpy()
    expect = np.stack([x[1, 0], x[3, 1], x[0, 2]])
    assert_almost_equal(last, expect)
    masked = nd.SequenceMask(nd.array(x), nd.array(lens),
                             use_sequence_length=True, value=-1).asnumpy()
    assert (masked[3, 0] == -1).all() and (masked[1, 0] == x[1, 0]).all()
    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True).asnumpy()
    assert_almost_equal(rev[0, 0], x[1, 0])
    assert_almost_equal(rev[2, 2], x[2, 2])


def test_rnn_op_lstm_shapes_and_scan():
    T, B, I, H, L = 5, 2, 3, 4, 2
    from mxnet_tpu.ops.sequence import rnn_param_size
    psize = rnn_param_size(L, I, H, False, "lstm")
    params = nd.random.normal(0, 0.1, (psize,))
    x = nd.random.normal(0, 1, (T, B, I))
    h0 = nd.zeros((L, B, H))
    c0 = nd.zeros((L, B, H))
    outs = nd.RNN(x, params, h0, c0, state_size=H, num_layers=L, mode="lstm",
                  state_outputs=True)
    assert outs[0].shape == (T, B, H)
    assert outs[1].shape == (L, B, H)
    assert outs[2].shape == (L, B, H)
    # bidirectional
    psize = rnn_param_size(1, I, H, True, "gru")
    params = nd.random.normal(0, 0.1, (psize,))
    outs = nd.RNN(x, params, nd.zeros((2, B, H)), state_size=H, num_layers=1,
                  bidirectional=True, mode="gru", state_outputs=True)
    assert outs[0].shape == (T, B, 2 * H)


def test_linalg_ops():
    a = np.random.randn(3, 3).astype("f")
    spd = a @ a.T + 3 * np.eye(3, dtype="f")
    chol = nd.linalg_potrf(nd.array(spd)).asnumpy()
    assert_almost_equal(chol @ chol.T, spd, rtol=1e-3, atol=1e-4)
    x = np.random.randn(3, 4).astype("f")
    y = np.random.randn(4, 5).astype("f")
    c = np.random.randn(3, 5).astype("f")
    out = nd.linalg.gemm(nd.array(x), nd.array(y), nd.array(c), alpha=2.0,
                         beta=0.5).asnumpy()
    assert_almost_equal(out, 2 * (x @ y) + 0.5 * c, rtol=1e-4)


def test_random_ops():
    u = nd.random.uniform(0, 1, (1000,))
    assert 0 <= u.asnumpy().min() and u.asnumpy().max() <= 1
    n = nd.random.normal(0, 1, (2000,))
    assert abs(n.asnumpy().mean()) < 0.15
    p = nd.random.poisson(3.0, (500,))
    assert abs(p.asnumpy().mean() - 3.0) < 0.5
    r = nd.random.randint(0, 10, (100,))
    assert r.dtype == np.int32 and r.asnumpy().max() < 10
    # seeded reproducibility
    mx.random.seed(7)
    a = nd.random.uniform(0, 1, (5,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.uniform(0, 1, (5,)).asnumpy()
    assert_almost_equal(a, b)


def test_dropout_modes():
    x = nd.ones((100, 100))
    with mx.autograd.train_mode():
        y = nd.Dropout(x, p=0.5)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7
    y_test = nd.Dropout(x, p=0.5)  # predict mode: identity
    assert (y_test.asnumpy() == 1).all()


def test_leaky_relu_variants():
    x = np.array([-2.0, -0.5, 0.5, 2.0], "f")
    out = nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1).asnumpy()
    assert_almost_equal(out, np.where(x > 0, x, 0.1 * x), rtol=1e-5)
    elu = nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0).asnumpy()
    assert_almost_equal(elu, np.where(x > 0, x, np.expm1(x)), rtol=1e-4)


def test_upsampling_nearest():
    x = np.arange(4).reshape(1, 1, 2, 2).astype("f")
    out = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest").asnumpy()
    assert out.shape == (1, 1, 4, 4)
    assert (out[0, 0, :2, :2] == x[0, 0, 0, 0]).all()


def test_block_grad_and_make_loss():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = nd.BlockGrad(x) * 3 + x
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), np.ones(2))


def test_multibox_float_params():
    """Float tuple params (sizes/ratios/variances) survive canonization —
    regression: 'shape'-typed coercion truncated 0.2 -> 0."""
    from mxnet_tpu import nd
    feat = nd.random.uniform(shape=(1, 4, 4, 4))
    anc = nd.MultiBoxPrior(feat, sizes=(0.2, 0.35), ratios=(1.0, 2.0, 0.5),
                           clip=True)
    a = anc.asnumpy()[0]
    assert a.shape == (4 * 4 * 4, 4)
    w = a[:, 2] - a[:, 0]
    assert (w > 0.05).all()  # sizes kept as floats, not truncated to 0
    assert np.unique(np.round(w, 3)).size >= 3  # distinct anchor widths


def test_proposal_op():
    """RPN proposals (parity: contrib/proposal.cc): fixed-shape roi output,
    boxes clipped to the image."""
    cls = nd.random.uniform(shape=(2, 24, 8, 8))
    bbox = nd.random.normal(shape=(2, 48, 8, 8)) * 0.1
    im_info = nd.array([[128, 128, 1.0], [128, 128, 1.0]])
    rois = nd.Proposal(cls, bbox, im_info, rpn_pre_nms_top_n=200,
                       rpn_post_nms_top_n=50, feature_stride=16)
    assert rois.shape == (100, 5)
    r = rois.asnumpy()
    assert (r[:50, 0] == 0).all() and (r[50:, 0] == 1).all()
    assert (r[:, 1:] >= 0).all()
    assert (r[:, 3] <= 127).all() and (r[:, 4] <= 127).all()
    # x2 >= x1, y2 >= y1
    assert (r[:, 3] >= r[:, 1]).all() and (r[:, 4] >= r[:, 2]).all()


def test_deformable_convolution_zero_offset():
    """Zero offsets reduce deformable conv to standard conv."""
    data = nd.random.uniform(shape=(1, 4, 8, 8))
    w = nd.random.normal(shape=(6, 4, 3, 3)) * 0.1
    b = nd.zeros((6,))
    off = nd.zeros((1, 18, 8, 8))
    out = nd.DeformableConvolution(data, off, w, b, kernel=(3, 3),
                                   pad=(1, 1), num_filter=6)
    ref = nd.Convolution(data, w, b, kernel=(3, 3), pad=(1, 1), num_filter=6)
    assert_almost_equal(out.asnumpy(), ref.asnumpy(), rtol=1e-4, atol=1e-5)


def test_deformable_convolution_interleaved_offsets():
    """Offset channels follow the reference deformable_im2col layout:
    channel 2*(i*kw+j) = y-offset, 2*(i*kw+j)+1 = x-offset of tap (i,j)."""
    rs = np.random.RandomState(1)
    data = nd.array(rs.rand(1, 1, 6, 6).astype("f"))
    # weight that selects ONLY kernel tap (0,0) of a 3x3 kernel
    w_np = np.zeros((1, 1, 3, 3), "f")
    w_np[0, 0, 0, 0] = 1.0
    w = nd.array(w_np)
    b = nd.zeros((1,))
    off = nd.zeros((1, 18, 6, 6))
    # x-offset of tap (0,0) lives in channel 1
    off[:, 1, :, :] = 1.0
    out = nd.DeformableConvolution(data, off, w, b, kernel=(3, 3),
                                   pad=(1, 1), num_filter=1)
    # tap (0,0) samples (h-1, w-1); +1 x-offset moves it to (h-1, w)
    d = data.asnumpy()[0, 0]
    expected = np.zeros_like(d)
    expected[1:, :] = d[:-1, :]
    assert_almost_equal(out.asnumpy()[0, 0], expected, rtol=1e-4, atol=1e-5)


def test_deformable_convolution_shift_offset():
    """A +1-pixel x-offset equals shifting the input left by one."""
    rs = np.random.RandomState(0)
    data_np = rs.rand(1, 1, 6, 8).astype("f")
    w = nd.array(np.ones((1, 1, 1, 1), "f"))
    b = nd.zeros((1,))
    off = nd.zeros((1, 2, 6, 8))
    off[:, 1, :, :] = 1.0  # x offset +1 for the single kernel element
    out = nd.DeformableConvolution(nd.array(data_np), off, w, b,
                                   kernel=(1, 1), num_filter=1)
    assert_almost_equal(out.asnumpy()[0, 0, :, :-1], data_np[0, 0, :, 1:],
                        rtol=1e-5, atol=1e-6)


def test_psroi_pooling():
    """Constant score maps pool to the map's constant per output cell."""
    k, D = 2, 3
    maps = np.zeros((1, D * k * k, 8, 8), "f")
    for ch in range(D * k * k):
        maps[0, ch] = ch
    rois = nd.array([[0, 0, 0, 16, 16]])
    out = nd.PSROIPooling(nd.array(maps), rois, spatial_scale=0.5,
                          output_dim=D, pooled_size=k)
    got = out.asnumpy()[0]
    for d in range(D):
        for i in range(k):
            for j in range(k):
                assert abs(got[d, i, j] - (d * k * k + i * k + j)) < 1e-4


def test_psroi_pooling_group_size():
    """group_size < pooled_size buckets cells into score-map groups
    (psroi_pooling.cc channel formula (d*gs+gh)*gs+gw)."""
    k, gs, D = 4, 2, 1
    maps = np.zeros((1, D * gs * gs, 8, 8), "f")
    for ch in range(D * gs * gs):
        maps[0, ch] = ch
    rois = nd.array([[0, 0, 0, 16, 16]])
    out = nd.PSROIPooling(nd.array(maps), rois, spatial_scale=0.5,
                          output_dim=D, pooled_size=k, group_size=gs)
    got = out.asnumpy()[0, 0]
    for i in range(k):
        for j in range(k):
            expected = (i * gs // k) * gs + (j * gs // k)
            assert abs(got[i, j] - expected) < 1e-4, (i, j, got[i, j])


# -- round-2 op gap closures (VERDICT missing #7) ---------------------------
def test_sample_vector_param_samplers():
    """Per-element distribution parameters (parity: sample_op.cc
    _sample_gamma/exponential/poisson/negative_binomial/gnb)."""
    mx.random.seed(7)
    alpha = nd.array(np.array([1.0, 4.0], "f"))
    beta = nd.array(np.array([1.0, 2.0], "f"))
    g = nd.sample_gamma(alpha, beta, shape=(2000,))
    assert g.shape == (2, 2000)
    m = g.asnumpy().mean(axis=1)
    assert abs(m[0] - 1.0) < 0.2 and abs(m[1] - 8.0) < 0.8  # mean=a*b

    lam = nd.array(np.array([0.5, 4.0], "f"))
    e = nd.sample_exponential(lam, shape=(2000,))
    me = e.asnumpy().mean(axis=1)
    assert abs(me[0] - 2.0) < 0.3 and abs(me[1] - 0.25) < 0.05

    po = nd.sample_poisson(lam, shape=(2000,))
    mp = po.asnumpy().mean(axis=1)
    assert abs(mp[0] - 0.5) < 0.1 and abs(mp[1] - 4.0) < 0.3

    k = nd.array(np.array([2.0, 8.0], "f"))
    prob = nd.array(np.array([0.5, 0.5], "f"))
    nb = nd.sample_negative_binomial(k, prob, shape=(3000,))
    mnb = nb.asnumpy().mean(axis=1)   # mean = k(1-p)/p
    assert abs(mnb[0] - 2.0) < 0.4 and abs(mnb[1] - 8.0) < 1.0

    mu = nd.array(np.array([2.0, 5.0], "f"))
    al = nd.array(np.array([0.2, 0.5], "f"))
    gnb = nd.sample_generalized_negative_binomial(mu, al, shape=(3000,))
    mg = gnb.asnumpy().mean(axis=1)   # mean = mu
    assert abs(mg[0] - 2.0) < 0.4 and abs(mg[1] - 5.0) < 0.9


def test_khatri_rao():
    A = np.array([[1., 2.], [3., 4.]], "f")          # (2, 2)
    B = np.array([[1., 0.], [0., 1.], [2., 3.]], "f")  # (3, 2)
    out = nd.khatri_rao(nd.array(A), nd.array(B))
    assert out.shape == (6, 2)
    exp = np.stack([np.kron(A[:, j], B[:, j]) for j in range(2)], axis=1)
    assert_almost_equal(out.asnumpy(), exp, rtol=1e-6)


def test_deformable_psroi_pooling_matches_psroi_at_zero_offsets():
    """With no_trans (zero offsets) the deformable op reduces to plain
    position-sensitive pooling over the score maps."""
    rs = np.random.RandomState(0)
    k, D, gs = 2, 3, 2
    C = D * gs * gs
    data = nd.array(rs.rand(1, C, 8, 8).astype("f"))
    rois = nd.array(np.array([[0, 0, 0, 7, 7]], "f"))
    out = nd.DeformablePSROIPooling(data, rois, nd.zeros((1, 2, k, k)),
                                    spatial_scale=1.0, output_dim=D,
                                    group_size=gs, pooled_size=k,
                                    no_trans=True)
    assert out.shape == (1, D, k, k)
    assert np.isfinite(out.asnumpy()).all()
    # channel selection rule: output (d, i, j) pools channel (d*gs+gh)*gs+gw
    d_np = data.asnumpy()[0]
    got = out.asnumpy()[0]
    for d in range(D):
        for i in range(k):
            ch = (d * gs + (i * gs // k)) * gs + 0
            lo = d_np[ch].min() - 1e-5
            hi = d_np[ch].max() + 1e-5
            assert lo <= got[d, i, 0] <= hi


def test_deformable_psroi_pooling_offsets_differentiable():
    from mxnet_tpu import autograd
    rs = np.random.RandomState(1)
    k, D, gs = 2, 1, 1
    data = nd.array(rs.rand(1, D * gs * gs, 8, 8).astype("f"))
    trans = nd.array(rs.uniform(-0.1, 0.1, (1, 2, k, k)).astype("f"))
    rois = nd.array(np.array([[0, 1, 1, 6, 6]], "f"))
    trans.attach_grad()
    with autograd.record():
        out = nd.DeformablePSROIPooling(data, rois, trans,
                                        spatial_scale=1.0, output_dim=D,
                                        group_size=gs, pooled_size=k,
                                        trans_std=0.5)
        out.sum().backward()
    g = trans.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_convolution_v1_alias():
    x = nd.array(np.random.RandomState(0).rand(1, 2, 5, 5).astype("f"))
    w = nd.array(np.random.RandomState(1).rand(3, 2, 3, 3).astype("f"))
    b = nd.zeros((3,))
    a = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=3)
    v1 = nd.Convolution_v1(x, w, b, kernel=(3, 3), num_filter=3)
    assert_almost_equal(a.asnumpy(), v1.asnumpy(), rtol=1e-6)


def test_ctc_loss_lengths_symbol_eager_parity():
    """CTC with per-sequence lengths: the symbol graph binds inputs
    positionally with the unused data_lengths slot elided — must match the
    eager keyword call (regression: slot shift silently dropped lengths)."""
    import mxnet_tpu as mx
    rs = np.random.RandomState(0)
    P = rs.randn(5, 2, 4).astype("f")
    L = np.array([[1, 2, 3, 0], [2, 1, 0, 0]], "f")
    LL = np.array([3, 2], "f")
    eager = nd.CTCLoss(nd.array(P), nd.array(L),
                       label_lengths=nd.array(LL),
                       use_label_lengths=True, blank_label="last").asnumpy()
    s = mx.sym.CTCLoss(mx.sym.Variable("pred"), mx.sym.Variable("label"),
                       label_lengths=mx.sym.Variable("ll"),
                       use_label_lengths=True, blank_label="last")
    ex = s.simple_bind(mx.cpu(), pred=(5, 2, 4), label=(2, 4), ll=(2,))
    sym_out = ex.forward(pred=P, label=L, ll=LL)[0].asnumpy()
    assert_almost_equal(sym_out, eager, rtol=1e-5)
    # lengths actually bite: truncating label 2's pad changes the loss
    full = nd.CTCLoss(nd.array(P), nd.array(L),
                      label_lengths=nd.array([4.0, 4.0]),
                      use_label_lengths=True, blank_label="last").asnumpy()
    assert abs(full[1] - eager[1]) > 1e-3


def test_pooling_avg_backward_under_jit():
    """Windowed avg/sum pooling must differentiate inside the compiled
    executor (regression: jax 0.9 can't linearize reduce_window_sum under
    jit; pooling lowers to a grouped conv instead).  Non-overlapping
    windows give an exact analytic grad: 1/kernel_volume everywhere."""
    import mxnet_tpu as mx
    s = mx.sym.Pooling(mx.sym.Variable("data"), kernel=(2, 2),
                       stride=(2, 2), pool_type="avg")
    ex = s.simple_bind(mx.cpu(), grad_req="write", data=(1, 2, 4, 4))
    x = np.arange(32, dtype="f").reshape(1, 2, 4, 4)
    ex.arg_dict["data"][:] = x
    out = ex.forward(is_train=True)[0].asnumpy()
    ref = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    assert_almost_equal(out, ref, rtol=1e-6)
    ex.backward()
    assert_almost_equal(ex.grad_dict["data"].asnumpy(),
                        np.full_like(x, 0.25), rtol=1e-6)
    # sum pooling too
    s2 = mx.sym.Pooling(mx.sym.Variable("data"), kernel=(2, 2),
                        stride=(2, 2), pool_type="sum")
    ex2 = s2.simple_bind(mx.cpu(), grad_req="write", data=(1, 2, 4, 4))
    ex2.arg_dict["data"][:] = x
    out2 = ex2.forward(is_train=True)[0].asnumpy()
    assert_almost_equal(out2, ref * 4, rtol=1e-6)
    ex2.backward()
    assert_almost_equal(ex2.grad_dict["data"].asnumpy(),
                        np.ones_like(x), rtol=1e-6)
