"""Reference binary checkpoint interop (mxnet_tpu/legacy_format.py;
parity: src/ndarray/ndarray.cc:844-1050 NDArray::Save/Load + the
kMXAPINDArrayListMagic container, tests/python/unittest/
test_ndarray.py:263 test_ndarray_legacy_load).

The v0 stream in the first test is SYNTHESIZED from the wire spec —
byte-for-byte the layout of the reference's legacy_ndarray.v0 fixture
(6 x arange(128): uint64 magic 0x112 + reserved, count, per record
ndim-as-magic + uint32 dims + int32 ctx pair + int32 dtype flag + raw
f32 blob, empty name vector) — so the reader is pinned against an
independently-constructed byte stream, not against its own writer."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def _v0_stream(arrays):
    out = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", len(arrays))]
    for a in arrays:
        out.append(struct.pack("<I", a.ndim))
        out += [struct.pack("<I", d) for d in a.shape]
        out.append(struct.pack("<ii", 1, 0))          # cpu context
        out.append(struct.pack("<i", 0))              # float32 flag
        out.append(np.ascontiguousarray(a, "f").tobytes())
    out.append(struct.pack("<Q", 0))                  # no names -> list
    return b"".join(out)


def test_legacy_v0_list_loads(tmp_path):
    ref = [np.arange(128, dtype="f") for _ in range(6)]
    p = tmp_path / "legacy.v0"
    p.write_bytes(_v0_stream(ref))
    got = mx.nd.load(str(p))
    assert isinstance(got, list) and len(got) == 6
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a.asnumpy(), b)


def test_v2_dense_roundtrip_names_and_dtypes(tmp_path):
    rs = np.random.RandomState(0)
    src = {"arg:w": mx.nd.array(rs.normal(0, 1, (3, 4)).astype("f")),
           "aux:m": mx.nd.array(np.arange(5, dtype="int64")),
           "half": mx.nd.array(np.arange(6, dtype="float16").reshape(2, 3)),
           "bytes": mx.nd.array(np.arange(4, dtype="uint8"))}
    p = str(tmp_path / "m.params")
    mx.nd.save_reference_format(p, src)
    from mxnet_tpu.legacy_format import is_reference_format
    assert is_reference_format(p)
    back = mx.nd.load(p)  # transparent sniff, no explicit API needed
    assert set(back) == set(src)
    for k in src:
        np.testing.assert_array_equal(back[k].asnumpy(),
                                      src[k].asnumpy())
        assert str(back[k].dtype) == str(src[k].dtype), k


def test_v2_sparse_roundtrip(tmp_path):
    from mxnet_tpu.ndarray import sparse as sp
    rsp = sp.row_sparse_array(
        (np.array([[1.0, 2], [3, 4]], "f"), np.array([1, 3])),
        shape=(5, 2))
    csr = sp.csr_matrix(
        (np.array([1.0, 2, 3], "f"), np.array([0, 2, 1]),
         np.array([0, 1, 2, 3, 3])), shape=(4, 3))
    p = str(tmp_path / "s.params")
    mx.nd.save_reference_format(p, {"r": rsp, "c": csr})
    back = mx.nd.load(p)
    assert back["r"].stype == "row_sparse" and back["c"].stype == "csr"
    for k, ref in (("r", rsp), ("c", csr)):
        np.testing.assert_array_equal(
            back[k].tostype("default").asnumpy(),
            ref.tostype("default").asnumpy())


def test_bf16_widens_to_f32_on_save(tmp_path):
    a = mx.nd.array(np.arange(4, dtype="f")).astype("bfloat16")
    p = str(tmp_path / "b.params")
    mx.nd.save_reference_format(p, [a])
    (back,) = mx.nd.load(p)
    # bf16 has no reference-era flag: widened losslessly to f32
    assert str(back.dtype) == "float32"
    np.testing.assert_array_equal(back.asnumpy(),
                                  a.asnumpy().astype("f"))


def test_reference_checkpoint_feeds_module(tmp_path):
    """The real switching-user path: a checkpoint whose .params is the
    reference BINARY format (symbol JSON + arg:/aux: keyed arrays)
    loads through mx.model.load_checkpoint and serves a Module."""
    from mxnet_tpu import sym
    from mxnet_tpu.io import DataBatch, DataDesc
    rs = np.random.RandomState(1)
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=3, name="fc"),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[DataDesc("data", (4, 6), np.float32)],
             label_shapes=[DataDesc("softmax_label", (4,), np.float32)])
    mod.init_params(mx.init.Xavier())
    arg, aux = mod.get_params()
    x = rs.normal(0, 1, (4, 6)).astype("f")
    mod.forward(DataBatch(data=[mx.nd.array(x)], label=None, pad=0,
                          index=None), is_train=False)
    want = mod.get_outputs()[0].asnumpy()

    prefix = str(tmp_path / "refck")
    with open(prefix + "-symbol.json", "w") as f:
        f.write(net.tojson())
    blob = {f"arg:{k}": v for k, v in arg.items()}
    blob.update({f"aux:{k}": v for k, v in aux.items()})
    mx.nd.save_reference_format(prefix + "-0003.params", blob)

    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 3)
    mod2 = mx.mod.Module(sym2)
    mod2.bind(data_shapes=[DataDesc("data", (4, 6), np.float32)],
              label_shapes=[DataDesc("softmax_label", (4,), np.float32)])
    mod2.set_params(arg2, aux2)
    mod2.forward(DataBatch(data=[mx.nd.array(x)], label=None, pad=0,
                           index=None), is_train=False)
    np.testing.assert_allclose(mod2.get_outputs()[0].asnumpy(), want,
                               atol=1e-6)


def test_v2_and_v1_streams_synthesized_from_spec(tmp_path):
    """V1/V2 records hand-packed from the wire spec — uint32 ndim +
    INT64 dims (V1 is 'the int64_t TShape version', ndarray.cc:843) —
    so the reader's dim width is pinned independently of the writer."""
    def shp(s):
        return struct.pack("<I", len(s)) + b"".join(
            struct.pack("<q", d) for d in s)

    a = np.arange(12, dtype="f").reshape(3, 4)
    v2 = (struct.pack("<Ii", 0xF993FAC9, 0) + shp(a.shape)
          + struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
    b = np.arange(5, dtype="int64")
    v1 = (struct.pack("<I", 0xF993FAC8) + shp(b.shape)
          + struct.pack("<ii", 1, 0) + struct.pack("<i", 6) + b.tobytes())
    name = b"w"
    blob = (struct.pack("<QQQ", 0x112, 0, 2) + v2 + v1
            + struct.pack("<Q", 2)
            + struct.pack("<Q", 1) + name
            + struct.pack("<Q", 1) + b"b")
    p = tmp_path / "v2v1.params"
    p.write_bytes(blob)
    got = mx.nd.load(str(p))
    np.testing.assert_array_equal(got["w"].asnumpy(), a)
    np.testing.assert_array_equal(got["b"].asnumpy(), b)
    assert str(got["b"].dtype) == "int64"


def test_zero_d_arrays_rejected_on_save(tmp_path):
    # ndim 0 means "none" on the wire; a 0-d scalar would corrupt every
    # following record, so the writer refuses loudly
    with pytest.raises(MXNetError):
        mx.nd.save_reference_format(str(tmp_path / "z.params"),
                                    [mx.nd.array(np.float32(3.0))])


def test_old_schema_symbol_json_loads():
    """Pre-1.0 symbol JSON (the save_000800.json generation: 'param' /
    'attr' keys, 2-element inputs, backward_source_id) must load and
    execute — synthesized here from the old schema, mirroring the
    reference fixture's shape."""
    import json
    doc = {
        "nodes": [
            {"op": "null", "param": {}, "name": "data", "inputs": [],
             "backward_source_id": -1,
             "attr": {"ctx_group": "stage1"}},
            {"op": "null", "param": {}, "name": "fc_weight",
             "inputs": [], "backward_source_id": -1},
            {"op": "null", "param": {}, "name": "fc_bias", "inputs": [],
             "backward_source_id": -1},
            {"op": "FullyConnected",
             "param": {"no_bias": "False", "num_hidden": "4"},
             "name": "fc", "inputs": [[0, 0], [1, 0], [2, 0]],
             "backward_source_id": -1},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[3, 0]],
    }
    s = mx.sym.load_json(json.dumps(doc))
    assert s.list_arguments() == ["data", "fc_weight", "fc_bias"]
    ex = s.simple_bind(mx.cpu(), grad_req="null", data=(2, 3))
    x = np.random.RandomState(0).normal(0, 1, (2, 3)).astype("f")
    ex.arg_dict["data"][:] = x
    ex.arg_dict["fc_weight"][:] = np.ones((4, 3), "f")
    ex.arg_dict["fc_bias"][:] = np.zeros((4,), "f")
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, x @ np.ones((3, 4), "f"), atol=1e-5)


def test_save_checkpoint_reference_format_roundtrip(tmp_path):
    """save_checkpoint(reference_format=True) writes a checkpoint whose
    .params is the reference binary container, and load_checkpoint
    reads it back identically (the reverse-migration convenience)."""
    from mxnet_tpu import sym
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=2, name="fc"),
        name="softmax")
    rs = np.random.RandomState(2)
    arg = {"fc_weight": mx.nd.array(rs.normal(0, 1, (2, 3)).astype("f")),
           "fc_bias": mx.nd.array(np.zeros(2, "f"))}
    prefix = str(tmp_path / "rf")
    mx.model.save_checkpoint(prefix, 7, net, arg, {},
                             reference_format=True)
    from mxnet_tpu.legacy_format import is_reference_format
    assert is_reference_format(prefix + "-0007.params")
    _, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    assert aux2 == {}
    for k in arg:
        np.testing.assert_array_equal(arg2[k].asnumpy(),
                                      arg[k].asnumpy())

    # plumbed through the primary training surfaces too
    from mxnet_tpu.io import DataDesc
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[DataDesc("data", (4, 3), np.float32)],
             label_shapes=[DataDesc("softmax_label", (4,), np.float32)])
    mod.init_params(mx.init.Xavier())
    mod.save_checkpoint(str(tmp_path / "m"), 1, reference_format=True)
    assert is_reference_format(str(tmp_path / "m-0001.params"))
    cb = mx.callback.do_checkpoint(str(tmp_path / "c"),
                                   reference_format=True)
    cb(0, net, arg, {})
    assert is_reference_format(str(tmp_path / "c-0001.params"))


def test_corrupt_and_mismatched_files_fail_loudly(tmp_path):
    p = tmp_path / "bad.params"
    ref = [np.arange(8, dtype="f")]
    p.write_bytes(_v0_stream(ref)[:-12])  # truncate inside the blob
    with pytest.raises(MXNetError):
        mx.nd.load(str(p))
    # implausible ndim (garbage after the container header)
    p.write_bytes(struct.pack("<QQQ", 0x112, 0, 1)
                  + struct.pack("<I", 4096))
    with pytest.raises(MXNetError):
        mx.nd.load(str(p))
