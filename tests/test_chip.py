"""mxnet_tpu.chip: device-kind -> peak FLOPs mapping + MFU accounting
(VERDICT r4 #1: MFU is the product bench's first-class number)."""
import math

from mxnet_tpu import chip


def test_peak_lookup_known_kinds():
    assert chip.peak_bf16_tflops("TPU v5p") == 459.0
    assert chip.peak_bf16_tflops("TPU v5e") == 197.0
    assert chip.peak_bf16_tflops("TPU v5 lite") == 197.0
    assert chip.peak_bf16_tflops("TPU v5litepod-8") == 197.0
    assert chip.peak_bf16_tflops("TPU v4") == 275.0
    assert chip.peak_bf16_tflops("TPU v3") == 123.0
    assert chip.peak_bf16_tflops("TPU v6 lite") == 918.0
    # bare "v5" (kind string without the e/p suffix) maps to the
    # conservative-for-MFU larger peak, not a crash
    assert chip.peak_bf16_tflops("TPU v5") == 459.0


def test_peak_lookup_unknown():
    assert chip.peak_bf16_tflops("cpu") is None
    assert chip.peak_bf16_tflops("") is None
    assert chip.peak_bf16_tflops("Radeon") is None


def test_mfu_known_chip():
    # 1577.63 img/s on a v5e: the r4 judge's own arithmetic (~20%)
    m = chip.mfu(1577.63, kind="TPU v5e")
    assert m["peak_bf16_tflops"] == 197.0
    assert math.isclose(m["mfu"], 1577.63 * 24.6e9 / 197e12, rel_tol=1e-3)
    assert 0.19 < m["mfu"] < 0.21
    assert "mfu_if_v5e" not in m


def test_mfu_unknown_chip_reports_both_classes():
    m = chip.mfu(1577.63, kind="mystery accelerator")
    assert m["mfu"] is None
    assert 0.19 < m["mfu_if_v5e"] < 0.21
    assert 0.08 < m["mfu_if_v5p"] < 0.09


def test_device_kind_never_raises(monkeypatch):
    # probing must stay hang/raise-safe even with a broken jax
    import sys
    monkeypatch.setitem(sys.modules, "jax", None)
    assert isinstance(chip.device_kind(), str)
