"""Optimizer / metric / initializer / lr_scheduler tests
(parity model: tests/python/unittest/test_optimizer.py, test_metric.py,
test_init.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


# ------------------------------------------------------------- optimizers

ALL_OPTS = ["sgd", "adam", "rmsprop", "adagrad", "adadelta", "ftrl",
            "adamax", "nadam", "sgld", "dcasgd"]


@pytest.mark.parametrize("name", ALL_OPTS)
def test_optimizer_decreases_quadratic(name):
    """Every optimizer should make progress on f(w) = ||w||^2 / 2."""
    opt = mx.optimizer.create(name, learning_rate=0.05)
    updater = mx.optimizer.get_updater(opt)
    w = nd.array(np.full((4, 4), 5.0, "f"))
    start = float((w.asnumpy() ** 2).sum())
    for _ in range(30):
        grad = w.copy()  # d/dw ||w||^2/2 = w
        updater(0, grad, w)
    end = float((w.asnumpy() ** 2).sum())
    assert end < start, (name, start, end)


def test_sgd_momentum_math():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    state = opt.create_state(0, nd.zeros((2,)))
    w = nd.array([1.0, 1.0])
    g = nd.array([1.0, 2.0])
    # step 1: mom = -lr*g ; w += mom
    opt.update(0, w, g, state)
    assert_almost_equal(w.asnumpy(), np.array([0.9, 0.8], "f"),
                        rtol=1e-5, atol=1e-6)
    # step 2: mom = 0.9*mom - lr*g
    opt.update(0, w, g, state)
    assert_almost_equal(w.asnumpy(),
                        np.array([0.9 - 0.19, 0.8 - 0.38], "f"),
                        rtol=1e-5, atol=1e-6)


def test_sgd_wd_rescale():
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.1, rescale_grad=0.5)
    w = nd.array([1.0])
    g = nd.array([2.0])
    opt.update(0, w, g, opt.create_state(0, w))
    # grad_eff = 0.5*2 + 0.1*1 = 1.1; w = 1 - 0.1*1.1
    assert_almost_equal(w.asnumpy(), np.array([0.89], "f"),
                        rtol=1e-5, atol=1e-6)


def test_adam_first_step():
    opt = mx.optimizer.Adam(learning_rate=0.1, beta1=0.9, beta2=0.999,
                            epsilon=1e-8)
    w = nd.array([1.0])
    g = nd.array([0.5])
    opt.update(0, w, g, opt.create_state(0, w))
    # bias-corrected first step ≈ lr * sign-ish step
    expected = 1.0 - 0.1 * 0.5 / (np.sqrt(0.25) + 1e-8) * \
        np.sqrt(1 - 0.999) / (1 - 0.9) * (1 - 0.9) / np.sqrt(1 - 0.999)
    assert abs(w.asnumpy()[0] - expected) < 1e-3


def test_multi_precision_sgd():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                           multi_precision=True)
    w16 = nd.array(np.ones(4, "f")).astype("float16")
    state = opt.create_state_multi_precision(0, w16)
    g16 = nd.array(np.full(4, 0.1, "f")).astype("float16")
    opt.update_multi_precision(0, w16, g16, state)
    assert w16.dtype == np.float16
    # fp32 master copy keeps full precision
    master = state[0] if isinstance(state, (tuple, list)) else state
    assert np.asarray(master.asnumpy()).dtype == np.float32


def test_lr_mult_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0)
    opt.set_lr_mult({0: 0.1})
    opt.set_wd_mult({0: 0.0})
    w = nd.array([1.0])
    opt.update(0, w, nd.array([1.0]), opt.create_state(0, w))
    assert_almost_equal(w.asnumpy(), np.array([0.9], "f"),
                        rtol=1e-5, atol=1e-6)


def test_updater_serialization():
    opt = mx.optimizer.Adam()
    updater = mx.optimizer.get_updater(opt)
    w, g = nd.ones((3,)), nd.ones((3,))
    updater(0, g, w)
    states = updater.get_states()
    updater2 = mx.optimizer.get_updater(mx.optimizer.Adam())
    updater2.set_states(states)


def test_optimizer_registry():
    assert isinstance(mx.optimizer.create("sgd"), mx.optimizer.SGD)
    with pytest.raises((ValueError, mx.base.MXNetError)):
        mx.optimizer.create("not_an_optimizer")


def test_idx_update_count_lr_decay():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = nd.array([100.0])
    for _ in range(4):
        opt.update(0, w, nd.array([0.0]), opt.create_state(0, w))
    # reference FactorScheduler fires when num_update crosses count+step
    # strictly: 4 updates, step=2 -> one decay
    assert abs(opt._get_lr(0) - 0.5) < 1e-6


# ------------------------------------------------------------- schedulers

def test_factor_scheduler():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.1)
    s.base_lr = 1.0
    assert abs(s(5) - 1.0) < 1e-9
    assert abs(s(11) - 0.1) < 1e-9
    assert abs(s(25) - 0.01) < 1e-9


def test_multifactor_scheduler():
    s = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1)
    s.base_lr = 1.0
    assert abs(s(3) - 1.0) < 1e-9
    assert abs(s(7) - 0.1) < 1e-9
    assert abs(s(20) - 0.01) < 1e-9


def test_poly_cosine_schedulers():
    p = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0)
    assert p(0) == 1.0
    assert p(100) < p(50) < p(0)
    c = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0)
    assert c(100) < c(50) < c(1)


# ---------------------------------------------------------------- metrics

def test_accuracy_metric():
    m = mx.metric.Accuracy()
    pred = nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-6


def test_topk_metric():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = nd.array([[0.1, 0.2, 0.7], [0.6, 0.3, 0.1]])
    label = nd.array([1, 2])
    m.update([label], [pred])
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_mse_mae_rmse():
    pred = nd.array([[1.0], [2.0]])
    label = nd.array([[1.5], [1.0]])
    for name, expected in [("mse", (0.25 + 1.0) / 2),
                           ("mae", (0.5 + 1.0) / 2),
                           ("rmse", np.sqrt((0.25 + 1.0) / 2))]:
        m = mx.metric.create(name)
        m.update([label], [pred])
        assert abs(m.get()[1] - expected) < 1e-6, name


def test_f1_metric():
    m = mx.metric.F1()
    pred = nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    # tp=1 fp=1 fn=0 → precision=0.5 recall=1 → f1=2/3
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6


def test_perplexity_crossentropy():
    pred = nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = nd.array([0, 0])
    ce = mx.metric.CrossEntropy()
    ce.update([label], [pred])
    expected = -(np.log(0.5) + np.log(0.9)) / 2
    assert abs(ce.get()[1] - expected) < 1e-5
    pp = mx.metric.Perplexity(ignore_label=None)
    pp.update([label], [pred])
    assert abs(pp.get()[1] - np.exp(expected)) < 1e-4


def test_composite_metric():
    m = mx.metric.CompositeEvalMetric([mx.metric.Accuracy(),
                                       mx.metric.MSE()])
    pred = nd.array([[0.0, 1.0]])
    m.update([nd.array([1])], [pred])
    names, vals = m.get()
    assert len(names) == 2 and len(vals) == 2


def test_custom_metric():
    def feval(label, pred):
        return float(np.abs(label - pred).sum())
    m = mx.metric.np(feval, name="abs_sum")
    m.update([nd.array([1.0])], [nd.array([0.25])])
    assert abs(m.get()[1] - 0.75) < 1e-6


def test_metric_reset():
    m = mx.metric.Accuracy()
    m.update([nd.array([0])], [nd.array([[1.0, 0.0]])])
    m.reset()
    assert m.num_inst == 0


# ------------------------------------------------------------ initializers

def test_initializer_constants():
    for init, val in [(mx.init.Zero(), 0.0), (mx.init.One(), 1.0),
                      (mx.init.Constant(3.0), 3.0)]:
        arr = nd.empty((3, 3))
        init("weight", arr)
        assert_almost_equal(arr.asnumpy(), np.full((3, 3), val, "f"))


def test_uniform_normal_ranges():
    arr = nd.empty((100, 100))
    mx.init.Uniform(0.5)("weight", arr)
    a = arr.asnumpy()
    assert a.min() >= -0.5 and a.max() <= 0.5
    assert a.std() > 0.1
    mx.init.Normal(2.0)("weight", arr)
    assert abs(arr.asnumpy().std() - 2.0) < 0.1


def test_xavier_magnitude():
    arr = nd.empty((64, 64))
    mx.init.Xavier(factor_type="avg", magnitude=3.0)("weight", arr)
    scale = np.sqrt(3.0 / 64)
    a = arr.asnumpy()
    assert a.min() >= -scale - 1e-6 and a.max() <= scale + 1e-6


def test_orthogonal_init():
    arr = nd.empty((16, 16))
    mx.init.Orthogonal(scale=1.0)("weight", arr)
    a = arr.asnumpy()
    assert_almost_equal(a @ a.T, np.eye(16), rtol=1e-3, atol=1e-4)


def test_bilinear_init():
    arr = nd.empty((1, 1, 4, 4))
    mx.init.Bilinear()("upsampling_weight", arr)
    a = arr.asnumpy()
    assert a.max() <= 1.0 and a.min() >= 0.0


def test_init_by_name_patterns():
    # bias → zero, weight → chosen init (the InitDesc-driven dispatch)
    init = mx.init.Uniform(1.0)
    b = nd.empty((4,))
    init(mx.init.InitDesc("fc1_bias"), b)
    assert_almost_equal(b.asnumpy(), np.zeros(4, "f"))
    g = nd.empty((4,))
    init(mx.init.InitDesc("bn_gamma"), g)
    assert_almost_equal(g.asnumpy(), np.ones(4, "f"))


def test_mixed_initializer():
    m = mx.init.Mixed([".*bias", ".*"], [mx.init.Zero(), mx.init.One()])
    b, w = nd.empty((2,)), nd.empty((2,))
    m(mx.init.InitDesc("fc_bias"), b)
    m(mx.init.InitDesc("fc_weight"), w)
    assert_almost_equal(b.asnumpy(), np.zeros(2, "f"))
    assert_almost_equal(w.asnumpy(), np.ones(2, "f"))


def test_nag_matches_reference_formula():
    """NAG lookahead update against a hand-rolled numpy reference."""
    import mxnet_tpu as mx
    rs = np.random.RandomState(0)
    w = rs.randn(5).astype("f")
    opt = mx.optimizer.create("nag", learning_rate=0.1, momentum=0.9,
                              rescale_grad=1.0, wd=0.0)
    weight = mx.nd.array(w)
    state = opt.create_state(0, weight)
    mom_ref = np.zeros(5, "f")
    w_ref = w.copy()
    for step in range(5):
        g = rs.randn(5).astype("f")
        opt.update(0, weight, mx.nd.array(g), state)
        mom_ref = 0.9 * mom_ref + g
        w_ref = w_ref - 0.1 * (g + 0.9 * mom_ref)
        assert_almost_equal(weight.asnumpy(), w_ref, rtol=1e-5, atol=1e-6)


def test_nag_fused_updater_matches_per_key():
    import mxnet_tpu as mx
    from mxnet_tpu.optimizer import FusedUpdater
    rs = np.random.RandomState(1)
    opt1 = mx.optimizer.create("nag", learning_rate=0.05, momentum=0.9)
    opt2 = mx.optimizer.create("nag", learning_rate=0.05, momentum=0.9)
    fu = FusedUpdater(opt2)
    w1 = [mx.nd.array(rs.randn(4, 3).astype("f")) for _ in range(3)]
    w2 = [mx.nd.array(a.asnumpy()) for a in w1]
    s1 = [opt1.create_state(i, w) for i, w in enumerate(w1)]
    for step in range(4):
        gs = [rs.randn(4, 3).astype("f") for _ in range(3)]
        for i, (w, g, s) in enumerate(zip(w1, gs, s1)):
            opt1.update(i, w, mx.nd.array(g), s)
        fu.update_all(list(range(3)), [mx.nd.array(g) for g in gs], w2)
        for a, b in zip(w1, w2):
            assert_almost_equal(a.asnumpy(), b.asnumpy(), rtol=1e-5,
                                atol=1e-6)


def test_nag_row_sparse_lazy():
    """NAG preserves the lazy row-sparse invariant: untouched rows do not
    decay and their momentum does not advance."""
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import sparse
    rs = np.random.RandomState(2)
    opt = mx.optimizer.create("nag", learning_rate=0.1, momentum=0.9,
                              wd=0.1)
    w0 = rs.randn(6, 3).astype("f")
    weight = mx.nd.array(w0.copy())
    state = opt.create_state(0, weight)
    dense_rows = np.zeros((6, 3), "f")
    dense_rows[[1, 4]] = rs.randn(2, 3)
    grad = sparse.row_sparse_array(dense_rows)
    opt.update(0, weight, grad, state)
    w1 = weight.asnumpy()
    touched = [1, 4]
    untouched = [0, 2, 3, 5]
    assert np.abs(w1[untouched] - w0[untouched]).max() == 0.0
    assert np.abs(w1[touched] - w0[touched]).max() > 0.0


def test_nag_row_sparse_lazy_multi_precision():
    """The lazy row invariant holds under multi_precision too (the generic
    mp path would densify the gradient via astype)."""
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import sparse
    rs = np.random.RandomState(3)
    opt = mx.optimizer.create("nag", learning_rate=0.1, momentum=0.9,
                              wd=0.1, multi_precision=True)
    w0 = rs.randn(6, 3).astype(np.float32)
    weight = mx.nd.array(w0).astype("bfloat16")
    state = opt.create_state_multi_precision(0, weight)
    dense_rows = np.zeros((6, 3), "f")
    dense_rows[[2, 5]] = rs.randn(2, 3)
    grad = sparse.row_sparse_array(dense_rows)
    opt.update_multi_precision(0, weight, grad, state)
    w1 = weight.astype("float32").asnumpy()
    w0b = mx.nd.array(w0).astype("bfloat16").astype("float32").asnumpy()
    untouched = [0, 1, 3, 4]
    assert np.abs(w1[untouched] - w0b[untouched]).max() == 0.0
    assert np.abs(w1[[2, 5]] - w0b[[2, 5]]).max() > 0.0
