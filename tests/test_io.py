"""Data IO tests (parity model: tests/python/unittest/test_io.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarray_iter_basic():
    x = np.arange(40).reshape(10, 4).astype("f")
    y = np.arange(10).astype("f")
    it = mx.io.NDArrayIter(x, y, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    assert_almost_equal(batches[0].data[0].asnumpy(), x[:5])
    assert_almost_equal(batches[1].label[0].asnumpy(), y[5:])


def test_ndarray_iter_pad():
    x = np.arange(14).reshape(7, 2).astype("f")
    it = mx.io.NDArrayIter(x, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    # padded batch wraps around to the start
    assert_almost_equal(batches[-1].data[0].asnumpy()[1:], x[:2])


def test_ndarray_iter_discard():
    x = np.arange(14).reshape(7, 2).astype("f")
    it = mx.io.NDArrayIter(x, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_reset_shuffle():
    x = np.arange(20).reshape(10, 2).astype("f")
    it = mx.io.NDArrayIter(x, batch_size=5, shuffle=True)
    a = np.concatenate([b.data[0].asnumpy() for b in it])
    it.reset()
    b = np.concatenate([b.data[0].asnumpy() for b in it])
    # same elements, (almost surely) different order across epochs
    assert sorted(a.ravel()) == sorted(b.ravel())


def test_ndarray_iter_dict_data():
    it = mx.io.NDArrayIter({"a": np.zeros((6, 2)), "b": np.ones((6, 3))},
                           batch_size=2)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    batch = next(iter(it))
    assert len(batch.data) == 2


def test_resize_iter():
    x = np.zeros((10, 2), "f")
    it = mx.io.ResizeIter(mx.io.NDArrayIter(x, batch_size=2), 3)
    assert len(list(it)) == 3
    it.reset()
    assert len(list(it)) == 3


def test_prefetching_iter():
    x = np.arange(24).reshape(12, 2).astype("f")
    base = mx.io.NDArrayIter(x, batch_size=4)
    it = mx.io.PrefetchingIter(base)
    got = np.concatenate([b.data[0].asnumpy() for b in it])
    assert_almost_equal(got, x)
    it.reset()
    assert len(list(it)) == 3


def test_csv_iter(tmp_path):
    data = np.random.rand(8, 3).astype("f")
    labels = np.arange(8).astype("f")
    dpath, lpath = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels, delimiter=",")
    it = mx.io.CSVIter(data_csv=dpath, data_shape=(3,), label_csv=lpath,
                       batch_size=4)
    batches = list(it)
    assert len(batches) == 2
    assert_almost_equal(batches[0].data[0].asnumpy(), data[:4],
                        rtol=1e-5, atol=1e-6)


def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    fname = str(tmp_path / "x.rec")
    w = recordio.MXRecordIO(fname, "w")
    payloads = [bytes(range(i, i + 10)) for i in range(5)]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(fname, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                     str(tmp_path / "x.rec"), "w")
    for i in range(10):
        rec.write_idx(i, f"record{i}".encode())
    rec.close()
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                     str(tmp_path / "x.rec"), "r")
    assert rec.read_idx(7) == b"record7"
    assert rec.read_idx(2) == b"record2"
    rec.close()


def test_recordio_pack_unpack():
    from mxnet_tpu import recordio
    header = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack(header, b"payload")
    h2, content = recordio.unpack(s)
    assert h2.label == 3.0 and h2.id == 7
    assert content == b"payload"


def test_tensor_record_iter(tmp_path):
    data = np.random.rand(16, 3, 4, 4).astype("f")
    labels = np.arange(16).astype("f")
    path = str(tmp_path / "t.rec")
    mx.io.save_tensor_rec(path, data, labels)
    it = mx.io.TensorRecordIter(path, data_shape=(3, 4, 4), batch_size=4,
                                dtype="float32")
    got_d, got_l = [], []
    for b in it:
        got_d.append(b.data[0].asnumpy())
        got_l.append(b.label[0].asnumpy())
    assert_almost_equal(np.concatenate(got_d), data, rtol=1e-5, atol=1e-6)
    assert_almost_equal(np.concatenate(got_l), labels)


def test_data_desc_provide():
    x = np.zeros((6, 2, 3), "f")
    it = mx.io.NDArrayIter(x, batch_size=3)
    d = it.provide_data[0]
    assert d.shape == (3, 2, 3)


def test_image_record_iter_device_augment_matches_host(tmp_path):
    """device_augment=True (uint8 upload + fused on-device mirror/cast/
    normalize/transpose) must produce the same batches as the host
    numpy pipeline."""
    from mxnet_tpu import recordio
    rec = str(tmp_path / "t.rec")
    rs = np.random.RandomState(0)
    w = recordio.MXRecordIO(rec, "w")
    for i in range(8):
        img = (rs.rand(20, 20, 3) * 255).astype(np.uint8)
        w.write(recordio.pack_img(recordio.IRHeader(0, float(i), i, 0),
                                  img, quality=95, img_fmt=".png"))
    w.close()

    kw = dict(path_imgrec=rec, data_shape=(3, 16, 16), batch_size=4,
              mean_r=123.7, mean_g=116.3, mean_b=103.5,
              std_r=58.4, std_g=57.1, std_b=57.4,
              preprocess_threads=1, prefetch_buffer=1)
    host = mx.io.ImageRecordIter(**kw)
    dev = mx.io.ImageRecordIter(device_augment=True, **kw)
    for bh, bd in zip(host, dev):
        assert bd.data[0].dtype == np.float32
        np.testing.assert_allclose(bh.data[0].asnumpy(),
                                   bd.data[0].asnumpy(),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_array_equal(bh.label[0].asnumpy(),
                                      bd.label[0].asnumpy())

    # bf16 output dtype for feeding bf16-resident training directly
    dev16 = mx.io.ImageRecordIter(device_augment=True,
                                  device_dtype="bfloat16", **kw)
    b = next(iter(dev16))
    assert str(b.data[0].dtype) == "bfloat16"

    # rand_mirror: every device image must be the host image or its
    # horizontal flip
    host_m = mx.io.ImageRecordIter(rand_mirror=True, **kw)
    dev_m = mx.io.ImageRecordIter(rand_mirror=True, device_augment=True,
                                  **kw)
    bh = next(iter(host_m)).data[0].asnumpy()
    bd = next(iter(dev_m)).data[0].asnumpy()
    for i in range(4):
        match = (np.allclose(bd[i], bh[i], atol=1e-4) or
                 np.allclose(bd[i], bh[i][:, :, ::-1], atol=1e-4))
        assert match, i
