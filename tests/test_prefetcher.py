"""Prefetch-to-device pipeline (PR 2): ordering, shutdown, exception
propagation, and the shared AsyncPrefetcher core behind both
`gluon.data.prefetch_to_device` and `io.PrefetchingIter`."""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.data import (ArrayDataset, DataLoader,
                                  prefetch_to_device)
from mxnet_tpu.gluon.data.prefetcher import AsyncPrefetcher


def test_async_prefetcher_order_and_exhaustion():
    src = iter(range(10))
    pf = AsyncPrefetcher(lambda: next(src), depth=3)
    got = []
    while True:
        try:
            got.append(pf.get())
        except StopIteration:
            break
    assert got == list(range(10))
    # exhausted prefetcher keeps raising StopIteration, never hangs
    with pytest.raises(StopIteration):
        pf.get()


def test_async_prefetcher_transform_runs_on_worker():
    main = threading.get_ident()
    seen = []

    src = iter(range(4))

    def transform(x):
        seen.append(threading.get_ident())
        return x * 2

    pf = AsyncPrefetcher(lambda: next(src), depth=2, transform=transform)
    out = []
    while True:
        try:
            out.append(pf.get())
        except StopIteration:
            break
    assert out == [0, 2, 4, 6]
    assert all(t != main for t in seen)  # device_put overlaps the step


def test_async_prefetcher_exception_propagates_then_stops():
    """A worker failure re-raises in the consumer, THEN StopIteration —
    a consumer that catches the error won't hang on the next get()."""
    state = {"n": 0}

    def next_fn():
        state["n"] += 1
        if state["n"] > 2:
            raise ValueError("boom at batch 3")
        return state["n"]

    pf = AsyncPrefetcher(next_fn, depth=2)
    assert pf.get() == 1
    assert pf.get() == 2
    with pytest.raises(ValueError, match="boom at batch 3"):
        pf.get()
    with pytest.raises(StopIteration):
        pf.get()


def test_async_prefetcher_close_joins_worker():
    """close() stops a worker blocked on a full queue (slow consumer) and
    is idempotent."""
    ev = threading.Event()

    def next_fn():
        ev.set()
        return 1  # infinite source; queue fills, worker blocks on put

    pf = AsyncPrefetcher(next_fn, depth=1)
    assert ev.wait(timeout=5)
    pf.get()  # unblock at least one put so the stop flag is observed
    pf.close()
    deadline = time.time() + 5
    while pf._thread is not None and time.time() < deadline:
        time.sleep(0.01)
    pf.close()  # idempotent


def test_prefetch_to_device_dataloader_values():
    x = np.arange(64, dtype="f").reshape(16, 4)
    y = np.arange(16, dtype="f")
    loader = DataLoader(ArrayDataset(mx.nd.array(x), mx.nd.array(y)),
                        batch_size=4)
    plain = [(bx.asnumpy(), by.asnumpy()) for bx, by in loader]
    pre = [(bx.asnumpy(), by.asnumpy())
           for bx, by in prefetch_to_device(loader, depth=2)]
    assert len(plain) == len(pre) == 4
    for (ax, ay), (bx, by) in zip(plain, pre):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetch_to_device_is_device_resident():
    import jax
    dev = jax.devices()[0]
    loader = DataLoader(ArrayDataset(mx.nd.ones((8, 3)), mx.nd.ones((8,))),
                        batch_size=4)
    for bx, by in prefetch_to_device(loader, depth=2):
        assert dev in bx._data.devices()
        assert dev in by._data.devices()


def test_prefetch_to_device_reset_protocol():
    """reset() restarts the underlying DataIter source (io protocol)."""
    from mxnet_tpu.io import NDArrayIter
    x = mx.nd.array(np.arange(24, dtype="f").reshape(12, 2))
    it = prefetch_to_device(NDArrayIter(x, batch_size=4), depth=2)
    first = [b.data[0].asnumpy() for b in it]
    it.reset()
    second = [b.data[0].asnumpy() for b in it]
    assert len(first) == len(second) == 3
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)
    it.close()


def test_io_prefetching_iter_device_put():
    """io.PrefetchingIter(device=...) double-buffers HBM placement on the
    worker thread (shared AsyncPrefetcher core)."""
    import jax
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter
    x = mx.nd.array(np.arange(32, dtype="f").reshape(8, 4))
    y = mx.nd.array(np.arange(8, dtype="f"))
    pit = PrefetchingIter(NDArrayIter(x, y, batch_size=4), depth=2,
                          device=mx.cpu())
    dev = jax.devices()[0]
    n = 0
    for batch in pit:
        n += 1
        for arr in batch.data + batch.label:
            assert dev in arr._data.devices()
    assert n == 2
    pit.close()


# ---------------------------------------------------------------------------
# fault containment (ISSUE 12): respawn-once + corrupt-record skip budget
# ---------------------------------------------------------------------------
def _drain(pf):
    got = []
    while True:
        try:
            got.append(pf.get())
        except StopIteration:
            break
    return got


def test_worker_respawns_once_on_transient_io():
    from mxnet_tpu import faultinject as fi
    from mxnet_tpu.observability import metrics as M
    src = iter(range(12))
    before = M.PREFETCH_RESPAWNS.value
    plan = fi.FaultPlan().add("data.batch", "raise", exc=OSError,
                              times=1, after=4)
    with fi.active(plan):
        pf = AsyncPrefetcher(lambda: next(src), depth=2)
        got = _drain(pf)
    # the fire happens BEFORE the source read, so no record was
    # consumed: the respawned worker delivers the COMPLETE stream
    assert got == list(range(12))
    assert pf.respawns == 1
    assert M.PREFETCH_RESPAWNS.value == before + 1
    pf.close()


def test_second_transient_surfaces_to_consumer():
    from mxnet_tpu import faultinject as fi
    src = iter(range(12))
    plan = fi.FaultPlan().add("data.batch", "raise", exc=OSError,
                              times=2, after=4)
    with fi.active(plan):
        pf = AsyncPrefetcher(lambda: next(src), depth=2)
        got = []
        with pytest.raises(OSError):
            while True:
                try:
                    got.append(pf.get())
                except StopIteration:
                    break
    assert pf.respawns == 1  # one respawn spent, second error surfaced
    # sticky exhaustion after the error — never hangs
    with pytest.raises(StopIteration):
        pf.get()
    pf.close()


def test_corrupt_record_skip_budget():
    from mxnet_tpu import faultinject as fi
    from mxnet_tpu.observability import metrics as M
    from mxnet_tpu.resilience import DataCorruptionError
    before = M.DATA_RECORDS_SKIPPED.value
    src = iter(range(10))
    plan = fi.FaultPlan().add("data.batch", "raise",
                              exc=DataCorruptionError, times=2, after=3)
    with fi.active(plan):
        pf = AsyncPrefetcher(lambda: next(src), skip_budget=4)
        got = _drain(pf)
    # injected pre-read corruption consumes budget but loses no record
    assert got == list(range(10))
    assert pf.skipped == 2
    assert M.DATA_RECORDS_SKIPPED.value == before + 2
    pf.close()


def test_skip_budget_exhausts_to_typed_error():
    from mxnet_tpu import faultinject as fi
    from mxnet_tpu.resilience import (DataCorruptionError,
                                      DataSkipBudgetError)
    src = iter(range(10))
    plan = fi.FaultPlan().add("data.batch", "raise",
                              exc=DataCorruptionError, times=5, after=2)
    with fi.active(plan):
        pf = AsyncPrefetcher(lambda: next(src), skip_budget=2)
        with pytest.raises(DataSkipBudgetError) as ei:
            _drain(pf)
    assert isinstance(ei.value.__cause__, DataCorruptionError)
    assert pf.skipped == 2
    pf.close()


def test_skip_budget_zero_surfaces_corruption_directly():
    """Default budget (0): corruption surfaces typed and unskipped —
    skipping records is always an explicit opt-in."""
    from mxnet_tpu.resilience import DataCorruptionError

    def bad():
        raise DataCorruptionError("undecodable record")

    pf = AsyncPrefetcher(bad)
    with pytest.raises(DataCorruptionError):
        pf.get()
    assert pf.skipped == 0
    pf.close()


def test_real_corrupt_record_is_genuinely_skipped():
    """A decoder raising mid-read consumes the record: the skip budget
    drops THAT record and the stream continues with the rest."""
    from mxnet_tpu.resilience import DataCorruptionError
    src = iter(range(8))

    def decode():
        v = next(src)
        if v == 3:
            raise DataCorruptionError(f"record {v} undecodable")
        return v

    pf = AsyncPrefetcher(decode, skip_budget=1)
    assert _drain(pf) == [0, 1, 2, 4, 5, 6, 7]
    assert pf.skipped == 1
    pf.close()


def test_prefetching_iter_plumbs_skip_budget():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter
    x = mx.nd.array(np.arange(24, dtype="f").reshape(12, 2))
    pit = PrefetchingIter(NDArrayIter(x, batch_size=4), depth=2,
                          skip_budget=3)
    assert pit._pf._skip_budget == 3
    assert sum(1 for _ in pit) == 3
    pit.close()
