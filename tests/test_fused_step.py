"""MXNET_FUSED_STEP=1: the whole train step (fwd+bwd+optimizer) as ONE
donated XLA program (the engine-bulking limit).  Contract: numerically
identical training to the standard forward_backward+update path."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _fit(fused, optimizer, opt_params, dtype="float32", epochs=3):
    os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
    try:
        net = mx.sym.Variable("data")
        net = mx.sym.Activation(
            mx.sym.Convolution(net, num_filter=4, kernel=(3, 3),
                               pad=(1, 1), name="c1"), act_type="relu")
        net = mx.sym.BatchNorm(net, name="bn")
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=3,
                                  name="fc"), name="softmax")
        rs = np.random.RandomState(0)
        x = rs.normal(0, 1, (64, 3, 8, 8)).astype("f")
        y = rs.randint(0, 3, 64).astype("f")
        it = mx.io.NDArrayIter(x.astype(dtype), y, 16,
                               label_name="softmax_label")
        mx.random.seed(5)
        mod = mx.mod.Module(net)
        mod.bind(data_shapes=[("data", (16, 3, 8, 8), np.dtype(dtype))],
                 label_shapes=[("softmax_label", (16,))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(kvstore="tpu_sync", optimizer=optimizer,
                           optimizer_params=dict(opt_params))
        mod.fit(it, num_epoch=epochs)
        return {k: v.asnumpy().astype("f")
                for k, v in mod._exec.arg_dict.items()
                if k not in ("data", "softmax_label")}, mod
    finally:
        os.environ["MXNET_FUSED_STEP"] = "0"


@pytest.mark.parametrize("optimizer,params,dtype,tol", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
     "float32", 0.0),
    ("adam", {"learning_rate": 3e-3}, "float32", 0.0),
    # bf16 mp: the fused and standard programs are DIFFERENT XLA
    # fusions of the same math — their f32 masters drift ~5e-5/step
    # (measured; weights stay bit-identical per step until bf16
    # quantization surfaces the accumulated master delta), so the
    # 36-step bound is training-noise scale, not exactness
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9,
             "multi_precision": True}, "bfloat16", 0.06),
])
def test_fused_step_matches_standard(optimizer, params, dtype, tol):
    a, _ = _fit(False, optimizer, params, dtype)
    b, _ = _fit(True, optimizer, params, dtype)
    assert set(a) == set(b)
    for k in a:
        err = float(np.max(np.abs(a[k] - b[k])))
        assert err <= tol, (k, err)


def test_fused_step_one_program_per_batch(monkeypatch):
    """Steady state must be exactly ONE compiled execution per batch."""
    monkeypatch.setenv("MXNET_FUSED_STEP", "1")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    rs = np.random.RandomState(1)
    x = rs.normal(0, 1, (64, 8)).astype("f")
    y = rs.randint(0, 4, 64).astype("f")
    it = mx.io.NDArrayIter(x, y, 16, label_name="softmax_label")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    # warm epoch: compile + (possible) hyper upload
    mod.fit(it, num_epoch=1)
    fs = mod._fstep
    calls = []
    real = fs["fn"]

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)
    fs["fn"] = spy
    it.reset()
    for batch in it:
        mod.forward_backward(batch)
        mod.update()
    assert len(calls) == 4, len(calls)  # 64/16 batches, 1 program each


def test_fused_step_ineligible_falls_back(monkeypatch, caplog):
    """A non-fused optimizer must warn once and use the standard path."""
    monkeypatch.setenv("MXNET_FUSED_STEP", "1")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    rs = np.random.RandomState(1)
    it = mx.io.NDArrayIter(rs.normal(0, 1, (32, 8)).astype("f"),
                           rs.randint(0, 4, 32).astype("f"), 16,
                           label_name="softmax_label")
    mod = mx.mod.Module(net)
    # DCASGD has no fused_step
    mod.fit(it, num_epoch=1, optimizer="dcasgd",
            optimizer_params={"learning_rate": 0.05})
    # training happened through the standard path
    assert mod._exec.grad_dict["fc_weight"] is not None


def test_fused_step_get_params_survives_donation(monkeypatch):
    """get_params/epoch callbacks hold host-side mirrors; the fused
    step's buffer donation must not invalidate them, and the kvstore's
    weight copies must track training (a later pull would otherwise
    revert)."""
    monkeypatch.setenv("MXNET_FUSED_STEP", "1")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    rs = np.random.RandomState(2)
    x = rs.normal(0, 1, (32, 8)).astype("f")
    y = rs.randint(0, 4, 32).astype("f")
    it = mx.io.NDArrayIter(x, y, 16, label_name="softmax_label")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    held, snaps = [], []
    for epoch in range(3):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
        arg, _ = mod.get_params()
        held.append(arg["fc_weight"])
        snaps.append(arg["fc_weight"].asnumpy().copy())
    # more donated steps AFTER the last get_params, then read the held
    # mirror (pre-fix: the sync handed off the executor's live buffer,
    # the donation deleted it -> RuntimeError 'Array has been deleted')
    it.reset()
    for batch in it:
        mod.forward_backward(batch)
        mod.update()
    held[0].asnumpy()
    assert not np.allclose(snaps[0], snaps[-1])  # training moved
    # kvstore copy tracks training
    kv_w = mod._kvstore._store["fc_weight"].asnumpy()
    np.testing.assert_allclose(
        kv_w, mod._exec.arg_dict["fc_weight"].asnumpy(), rtol=1e-6)


# -- Gluon fused-compressed vs legacy per-key-compressed (ISSUE 3) ------
# The quantizer is elementwise, so bucket-level 2-bit quantization with
# flat residual buffers must reproduce the per-key error-feedback
# trajectory EXACTLY — losses, weights, and the residuals themselves.


def _gluon_mlp(depth=4, width=8, seed=11):
    from mxnet_tpu.gluon import nn
    import mxnet_tpu as mx_
    mx_.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        for _ in range(depth):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(1))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    return net


def _residual_snapshot(trainer):
    """Per-param-index residual arrays, from either representation:
    fused (flat per-bucket buffers, sliced by the bucketer's views) or
    legacy (per-key buffers held by the kvstore)."""
    if trainer._residuals is not None:
        bk = trainer._bucketer
        out = {}
        for j, i in enumerate(trainer._bucket_sig[1]):
            b, off, shape = bk.views[j]
            size = int(np.prod(shape)) if shape else 1
            out[i] = np.asarray(trainer._residuals[b][off:off + size])
        return out
    return {k: np.asarray(v).ravel()
            for k, v in trainer._kv._residuals.items()}


def _compressed_gluon_run(monkeypatch, fused_flag, steps=5):
    from mxnet_tpu import autograd, gluon
    monkeypatch.setenv("MXNET_FUSED_TRAINER", fused_flag)
    net = _gluon_mlp()
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.normal(0, 1, (8, 16)).astype("f"))
    y = mx.nd.array(rs.normal(0, 1, (8, 1)).astype("f"))
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9},
        kvstore="tpu_sync", update_on_kvstore=False,
        compression_params={"type": "2bit", "threshold": 0.5})
    losses, res_hist = [], []
    for _ in range(steps):
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(8)
        losses.append(float(l.asnumpy().ravel()[0]))
        res_hist.append(_residual_snapshot(trainer))
    weights = [p.data().asnumpy() for p in net.collect_params().values()]
    return losses, weights, res_hist


def _assert_compressed_parity(monkeypatch, steps=5):
    lf, wf, rf = _compressed_gluon_run(monkeypatch, "1", steps)
    ll, wl, rl = _compressed_gluon_run(monkeypatch, "0", steps)
    np.testing.assert_allclose(lf, ll, rtol=1e-5)
    for a, b in zip(wf, wl):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
    for step_f, step_l in zip(rf, rl):  # SAME residual evolution
        assert set(step_f) == set(step_l)
        for k in step_f:
            np.testing.assert_allclose(step_f[k], step_l[k],
                                       rtol=1e-5, atol=1e-7)


def test_gluon_compressed_fused_vs_legacy(monkeypatch):
    """Single-bucket: fused-compressed == legacy per-key-compressed
    over 5 steps (losses, final weights, per-step residuals)."""
    _assert_compressed_parity(monkeypatch)


def test_gluon_compressed_fused_vs_legacy_multi_bucket(monkeypatch):
    """A tiny MXNET_BUCKET_SIZE_MB forces one bucket per parameter —
    residual slicing across many buckets must not change the math."""
    monkeypatch.setenv("MXNET_BUCKET_SIZE_MB", "0.0001")
    _assert_compressed_parity(monkeypatch)


# -- Gluon fused row-sparse vs legacy per-key lazy update (ISSUE 20) ----
# The fused sparse leg (one gather→step→scatter program over all
# row-sparse keys, optimizer.update_sparse) must reproduce the
# reference-shaped lazy per-key loop: only the batch's rows move, only
# their optimizer-state slots advance.


def _sparse_gluon_run(monkeypatch, fused_flag, opt, opt_params, steps=5):
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    monkeypatch.setenv("MXNET_FUSED_TRAINER", fused_flag)
    monkeypatch.setenv("MXNET_WHOLE_STEP", "0")
    mx.random.seed(3)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Embedding(50, 8, sparse_grad=True))
        net.add(nn.Flatten())
        net.add(nn.Dense(1))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    rs = np.random.RandomState(0)
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), opt, dict(opt_params),
                            kvstore="tpu_sync", update_on_kvstore=False)
    losses = []
    for _ in range(steps):
        x = mx.nd.array(rs.randint(0, 50, (8, 4)).astype("f"))
        y = mx.nd.array(rs.normal(0, 1, (8, 1)).astype("f"))
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(8)
        losses.append(float(l.asnumpy().mean()))
    weights = [p.data().asnumpy() for p in net.collect_params().values()]
    return losses, weights


@pytest.mark.parametrize("opt,params", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 3e-3}),
])
def test_gluon_rowsparse_fused_vs_legacy(monkeypatch, opt, params):
    """ISSUE 20: fused sparse leg vs lazy per-key loop over 5 steps at
    rtol 1e-5 — the eager lazy optimizers compute their lr coefficients
    in python floats, the fused program in f32 on device, so bitwise is
    out of contract for the stepped rows (untouched rows never move on
    either path)."""
    lf, wf = _sparse_gluon_run(monkeypatch, "1", opt, params)
    ll, wl = _sparse_gluon_run(monkeypatch, "0", opt, params)
    np.testing.assert_allclose(lf, ll, rtol=1e-5, atol=1e-7)
    for a, b in zip(wf, wl):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
