"""Transformer LM family (model_zoo/transformer.py): causal masking,
flash-vs-dense attention parity, hybridized CachedOp equivalence, and a
training step.  (Beyond-reference capability — the long-context flagship;
the sharded legs live in tests/test_parallel.py ring/ulysses.)"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo.transformer import TransformerLM
from mxnet_tpu.test_utils import assert_almost_equal

V, T, B = 17, 12, 2


def make_net(attn_type="dense", seed=0):
    mx.random.seed(seed)
    net = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                        max_len=16, attn_type=attn_type)
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    return net


def copy_params(dst, src):
    # a forward pass materializes deferred-init params on both sides
    probe = mx.nd.zeros((1, 4))
    src(probe)
    dst(probe)
    sp = {k.split("_", 1)[1]: v for k, v in src.collect_params().items()}
    for k, v in dst.collect_params().items():
        v.set_data(sp[k.split("_", 1)[1]].data())


def test_causal_masking():
    """Perturbing future tokens must not change past logits."""
    rs = np.random.RandomState(0)
    net = make_net()
    t1 = rs.randint(0, V, (1, T)).astype("f")
    t2 = t1.copy()
    t2[0, 8:] = (t2[0, 8:] + 3) % V
    o1 = net(mx.nd.array(t1)).asnumpy()
    o2 = net(mx.nd.array(t2)).asnumpy()
    assert_almost_equal(o1[:, :8], o2[:, :8], rtol=1e-5, atol=1e-6)
    # and future logits DO change (the perturbation is visible)
    assert np.abs(o1[:, 8:] - o2[:, 8:]).max() > 1e-3


def test_flash_dense_parity():
    """The Pallas flash-attention path must match dense attention in both
    the forward logits and the parameter gradients."""
    rs = np.random.RandomState(1)
    dense = make_net("dense")
    flash = make_net("flash")
    copy_params(flash, dense)
    x = mx.nd.array(rs.randint(0, V, (B, T)).astype("f"))
    y = mx.nd.array(rs.randint(0, V, (B, T)).astype("f"))
    sce = gluon.loss.SoftmaxCrossEntropyLoss()

    outs, grads = [], []
    for net in (dense, flash):
        with autograd.record():
            logits = net(x)
            loss = sce(logits.reshape((-1, V)), y.reshape((-1,)))
        loss.backward()
        outs.append(logits.asnumpy())
        grads.append({k.split("_", 1)[1]: p.grad().asnumpy()
                      for k, p in net.collect_params().items()})
    assert_almost_equal(outs[0], outs[1], rtol=1e-4, atol=1e-5)
    for k in grads[0]:
        assert_almost_equal(grads[0][k], grads[1][k], rtol=1e-3, atol=1e-4,
                            names=(f"dense:{k}", f"flash:{k}"))


def test_hybridize_equivalence():
    """hybridize() compiles the stack into one CachedOp with identical
    numbers."""
    rs = np.random.RandomState(2)
    net = make_net()
    x = mx.nd.array(rs.randint(0, V, (B, T)).astype("f"))
    eager = net(x).asnumpy()
    net.hybridize()
    compiled = net(x).asnumpy()
    assert_almost_equal(eager, compiled, rtol=1e-5, atol=1e-6)


def test_training_reduces_loss():
    rs = np.random.RandomState(3)
    net = make_net()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    # fixed batch: loss must drop when memorizing it
    x = mx.nd.array(rs.randint(0, V, (4, T)).astype("f"))
    y = mx.nd.array(rs.randint(0, V, (4, T)).astype("f"))
    losses = []
    for _ in range(12):
        with autograd.record():
            logits = net(x)
            loss = sce(logits.reshape((-1, V)), y.reshape((-1,)))
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.7, losses


def test_generate_memorizes_sequence():
    """After memorizing one sequence, greedy generation from its prefix
    reproduces the continuation (decode loop + causal cache semantics)."""
    rs = np.random.RandomState(5)
    net = make_net()
    seq = rs.randint(0, V, (1, T)).astype("f")
    x = mx.nd.array(seq[:, :-1])
    y = mx.nd.array(seq[:, 1:])
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(60):
        with autograd.record():
            logits = net(x)
            loss = sce(logits.reshape((-1, V)), y.reshape((-1,)))
        loss.backward()
        trainer.step(1)
    prefix = mx.nd.array(seq[:, :4])
    out = net.generate(prefix, T - 4).asnumpy()[0]
    assert (out[4:] == seq[0, 4:]).mean() > 0.7, (out, seq)


def test_generate_static_matches_eager():
    """static_shapes decoding (fixed (B, max_len) buffer, one cached
    program per step kind) must produce the same greedy tokens as the
    growing-prefix eager reference, and must not recompile per step."""
    rs = np.random.RandomState(7)
    net = make_net(seed=3)
    prefix = mx.nd.array(rs.randint(0, V, (2, 5)).astype("f"))
    out_static = net.generate(prefix, 8, static_shapes=True).asnumpy()
    out_eager = net.generate(prefix, 8, static_shapes=False).asnumpy()
    assert out_static.shape == (2, 13)
    assert (out_static == out_eager).all(), (out_static, out_eager)
    # one compiled forward reused across all greedy steps: the step
    # block's CachedOp must hold exactly one shape specialization
    steps = net._decode_steps()
    cached_op = getattr(steps["greedy"], "_cached_op", None)
    if cached_op is not None and hasattr(cached_op._fwd, "_cache_size"):
        assert cached_op._fwd._cache_size() == 1


def test_generate_static_sampling():
    """temperature>0: the static path must draw the SAME tokens as the
    eager reference under a same-seeded rng (identical logits ->
    identical softmax -> identical draws), catching any off-by-one in
    the static read/write positions."""
    rs = np.random.RandomState(11)
    net = make_net(seed=4)
    prefix = mx.nd.array(rs.randint(0, V, (2, 4)).astype("f"))
    out_s = net.generate(prefix, 6, temperature=1.0,
                         rng=np.random.RandomState(0),
                         static_shapes=True).asnumpy()
    out_e = net.generate(prefix, 6, temperature=1.0,
                         rng=np.random.RandomState(0),
                         static_shapes=False).asnumpy()
    assert out_s.shape == (2, 10)
    assert (out_s[:, :4] == prefix.asnumpy()).all()
    assert ((out_s >= 0) & (out_s < V)).all()
    assert (out_s == out_e).all(), (out_s, out_e)


def test_generate_kv_cache_matches_eager():
    """kv_cache=True (mha_decode_step: O(Tmax*D)/token over per-layer
    K/V caches) must reproduce the eager reference exactly — greedy
    AND same-seeded sampling — catching cache-write position errors,
    mask off-by-ones, and any decode/training weight drift (the cell
    re-composes the same sub-blocks)."""
    rs = np.random.RandomState(17)
    net = make_net(seed=6)
    prefix = mx.nd.array(rs.randint(0, V, (2, 5)).astype("f"))
    out_kv = net.generate(prefix, 8, kv_cache=True).asnumpy()
    out_eager = net.generate(prefix, 8, static_shapes=False).asnumpy()
    assert (out_kv == out_eager).all(), (out_kv, out_eager)
    s_kv = net.generate(prefix, 5, temperature=1.0, kv_cache=True,
                        rng=np.random.RandomState(2)).asnumpy()
    s_eager = net.generate(prefix, 5, temperature=1.0,
                           static_shapes=False,
                           rng=np.random.RandomState(2)).asnumpy()
    assert (s_kv == s_eager).all(), (s_kv, s_eager)
    # conflicting strategy flags are an error, not a silent choice
    import pytest
    with pytest.raises(ValueError):
        net.generate(prefix, 2, kv_cache=True, static_shapes=False)
    # sp attention types decode over SHARDED caches and need an active
    # sp_scope — without one, both fail loudly (see the ring/ulysses
    # decode tests for the working sharded paths)
    from mxnet_tpu.base import MXNetError
    for sp_type in ("ring", "ulysses"):
        sp_net = make_net()
        for blk in sp_net.blocks._children:
            blk.attn._type = sp_type
        with pytest.raises(MXNetError):
            sp_net.generate(prefix, 2, kv_cache=True)


def test_generate_leaves_hybrid_state_alone():
    """generate() must not flip a deliberately-eager net into hybrid
    mode (the decode wrappers activate only their own flag)."""
    rs = np.random.RandomState(13)
    net = make_net(seed=5)
    assert net._active is False
    net.generate(mx.nd.array(rs.randint(0, V, (1, 3)).astype("f")), 2)
    assert net._active is False
    assert all(not b._active for b in net.blocks._children)


def test_beam_search_width1_is_greedy_and_scores_are_exact():
    """beam=1 must reproduce greedy KV decoding exactly, and the
    returned log-prob must equal the teacher-forced rescoring of the
    returned sequence (pins the combined-score/top-k/reindex
    bookkeeping inside the on-device beam step)."""
    rs = np.random.RandomState(23)
    net = make_net(seed=10)
    t0, new = 4, 7
    prompt = mx.nd.array(rs.randint(0, V, (2, t0)).astype("f"))
    greedy = net.generate(prompt, new, kv_cache=True).asnumpy()
    b1, s1 = net.beam_search(prompt, new, beam=1)
    assert (b1.asnumpy() == greedy).all()
    b3, s3 = net.beam_search(prompt, new, beam=3)
    # (no width-monotonicity assert: beam search keeps the W best
    # PREFIXES, so a wider beam is not provably >= greedy in score)
    # exact-score pin: rescore the winning sequences teacher-forced
    seq = b3.asnumpy()
    logits = net(b3).asnumpy()
    m = logits.max(-1, keepdims=True)
    lp = logits - m - np.log(np.exp(logits - m).sum(-1, keepdims=True))
    resc = np.array([
        sum(lp[b, t, int(seq[b, t + 1])] for t in range(t0 - 1,
                                                        t0 + new - 1))
        for b in range(seq.shape[0])])
    assert np.allclose(s3.asnumpy(), resc, atol=1e-3), (s3.asnumpy(),
                                                        resc)
    import pytest
    with pytest.raises(ValueError):
        net.beam_search(prompt, new, beam=0)


def test_save_load_roundtrip_with_decode_wrappers(tmp_path):
    """save_params/load_params must round-trip a net whose decode
    wrappers were already built (the wrappers share the net's
    parameters — building them must not add/rename anything), and the
    reloaded net must decode identically."""
    rs = np.random.RandomState(19)
    net = make_net(seed=8)
    prefix = mx.nd.array(rs.randint(0, V, (1, 4)).astype("f"))
    out1 = net.generate(prefix, 6, kv_cache=True).asnumpy()
    _ = net.generate(prefix, 2)               # static wrappers built too
    path = str(tmp_path / "lm.params")
    net.save_params(path)
    net2 = make_net(seed=9)                   # different init
    net2.load_params(path)
    out2 = net2.generate(prefix, 6, kv_cache=True).asnumpy()
    assert (out1 == out2).all(), (out1, out2)


def test_sequence_parallel_attn_types():
    """impl='ring'/'ulysses' as FIRST-CLASS attn types (SURVEY §5:
    sequence parallelism exposed through the same Gluon APIs): under
    parallel.sp_scope(mesh) the same TransformerLM forward runs the
    sharded kernels and matches the dense variant; without the scope it
    raises the documented error."""
    import jax
    import pytest
    from jax.sharding import Mesh
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.base import MXNetError

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("sp",))

    # op-level parity first (T divisible by the axis; H % n == 0 for
    # ulysses)
    rs = np.random.RandomState(0)
    qkv = nd.array(rs.normal(0, 1, (2, 16, 3 * 32)).astype("f"))
    ref = nd._contrib_multihead_attention(qkv, num_heads=4,
                                          impl="dense").asnumpy()
    for impl in ("ring", "ulysses"):
        with parallel.sp_scope(mesh):
            got = nd._contrib_multihead_attention(
                qkv, num_heads=4, impl=impl).asnumpy()
        assert_almost_equal(got, ref, rtol=1e-4, atol=1e-5,
                            names=(impl, "dense"))

    # scope required, loudly
    with pytest.raises(MXNetError):
        nd._contrib_multihead_attention(qkv, num_heads=4, impl="ring")

    # model-level: same params, dense vs ring forward agree
    dense_net = make_net("dense", seed=5)
    x = mx.nd.array(rs.randint(0, V, (B, T)).astype("f"))
    ref_out = dense_net(x).asnumpy()
    ring_net = make_net("ring", seed=5)  # same seed -> same init
    # T=12 does not divide 4 -> pad path must be handled by the caller;
    # use a divisible length for the sharded run
    x16 = mx.nd.array(rs.randint(0, V, (B, 16)).astype("f"))
    ref16 = dense_net(x16).asnumpy()
    with parallel.sp_scope(mesh):
        got16 = ring_net(x16).asnumpy()
    assert_almost_equal(got16, ref16, rtol=1e-4, atol=1e-5,
                        names=("ring-lm", "dense-lm"))
    assert ref_out.shape == (B, T, V)


def test_sequence_parallel_training_step():
    """The review-found gap: eager autograd THROUGH a ring-attention
    model (make_vjp places primals on the sp mesh and round-trips
    outputs/cotangents/grads).  One training step must run, produce
    finite grads matching the dense net's, and a custom scale must
    plumb through to the sharded kernels."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.test_utils import assert_almost_equal

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("sp",))
    rs = np.random.RandomState(2)
    x = mx.nd.array(rs.randint(0, V, (B, 16)).astype("f"))
    y = mx.nd.array(rs.randint(0, V, (B, 16)).astype("f"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def step(net, scoped):
        with autograd.record():
            out = net(x)
            loss = loss_fn(out.reshape((-1, V)), y.reshape((-1,)))
        loss.backward()
        grads = {k: p.grad().asnumpy()
                 for k, p in net.collect_params().items()}
        return float(loss.mean().asnumpy()), grads

    # gluon params initialize lazily at first forward: seed -> build ->
    # STEP for each net, so both first-draws start from the same state
    dense_net = make_net("dense", seed=9)
    l_ref, g_ref = step(dense_net, False)
    ring_net = make_net("ring", seed=9)
    with parallel.sp_scope(mesh):
        l_ring, g_ring = step(ring_net, True)
    assert abs(l_ring - l_ref) < 1e-4, (l_ring, l_ref)
    assert set(g_ring) == {k.replace("transformerlm1", "transformerlm0")
                           for k in g_ref} or len(g_ring) == len(g_ref)
    # param names differ only by the auto prefix counter; compare sorted
    for (ka, ga), (kb, gb) in zip(sorted(g_ring.items()),
                                  sorted(g_ref.items())):
        assert_almost_equal(ga, gb, rtol=1e-3, atol=1e-5,
                            names=(f"ring:{ka}", f"dense:{kb}"))

    # custom scale is honored by the sharded kernels
    qkv = nd.array(rs.normal(0, 1, (2, 16, 3 * 32)).astype("f"))
    ref = nd._contrib_multihead_attention(qkv, num_heads=4, impl="dense",
                                          scale=0.125).asnumpy()
    with parallel.sp_scope(mesh):
        got = nd._contrib_multihead_attention(
            qkv, num_heads=4, impl="ring", scale=0.125).asnumpy()
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-5,
                        names=("ring-scale", "dense-scale"))


def test_generate_top_k_top_p():
    """top_k=1 sampling must equal greedy on every strategy; nucleus
    filtering keeps tokens in-vocab and respects the prefix; the
    filtered distribution is renormalized (tiny top_p ~ greedy)."""
    rs = np.random.RandomState(37)
    net = make_net(seed=12)
    prefix = mx.nd.array(rs.randint(0, V, (2, 4)).astype("f"))
    greedy = net.generate(prefix, 6, kv_cache=True).asnumpy()
    for kw in ({"static_shapes": True}, {"static_shapes": False},
               {"kv_cache": True}):
        topk1 = net.generate(prefix, 6, temperature=1.0, top_k=1,
                             rng=np.random.RandomState(3), **kw).asnumpy()
        assert (topk1 == greedy).all(), (kw, topk1, greedy)
    tiny_p = net.generate(prefix, 6, temperature=1.0, top_p=1e-9,
                          rng=np.random.RandomState(4),
                          kv_cache=True).asnumpy()
    assert (tiny_p == greedy).all()
    out = net.generate(prefix, 6, temperature=1.2, top_k=5, top_p=0.9,
                       rng=np.random.RandomState(5),
                       kv_cache=True).asnumpy()
    assert out.shape == (2, 10)
    assert (out[:, :4] == prefix.asnumpy()).all()
    assert ((out >= 0) & (out < V)).all()


def test_ring_kv_decode_op_matches_dense():
    """impl='ring' mha_decode_step (sequence-sharded caches, distributed
    softmax via pmax/psum) must reproduce the dense decode step at every
    position when fed a sequence token-by-token on a CPU mesh."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu import nd, parallel

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("sp",))
    rs = np.random.RandomState(29)
    Bq, H, Tmax, D = 2, 4, 8, 32        # Tmax divisible by the axis
    dh = D // H
    qkv_seq = nd.array(rs.normal(0, 1, (Bq, Tmax, 3 * D)).astype("f"))
    kc_d = nd.zeros((Bq, H, Tmax, dh))
    vc_d = nd.zeros((Bq, H, Tmax, dh))
    kc_r = nd.zeros((Bq, H, Tmax, dh))
    vc_r = nd.zeros((Bq, H, Tmax, dh))
    for t in range(Tmax):
        step_qkv = nd.slice_axis(qkv_seq, axis=1, begin=t, end=t + 1)
        pos = nd.array([float(t)])
        od, kc_d, vc_d = nd.mha_decode_step(step_qkv, kc_d, vc_d, pos,
                                            num_heads=H)
        with parallel.sp_scope(mesh):
            orr, kc_r, vc_r = nd.mha_decode_step(step_qkv, kc_r, vc_r,
                                                 pos, num_heads=H,
                                                 impl="ring")
        assert_almost_equal(orr.asnumpy(), od.asnumpy(),
                            rtol=1e-4, atol=1e-5)
    assert_almost_equal(kc_r.asnumpy(), kc_d.asnumpy(), rtol=1e-5,
                        atol=1e-6)
    assert_almost_equal(vc_r.asnumpy(), vc_d.asnumpy(), rtol=1e-5,
                        atol=1e-6)


def test_ring_kv_decode_generate():
    """A ring-attention TransformerLM decodes with kv_cache=True under
    an sp_scope — sequence-sharded caches end to end — and emits the
    same greedy tokens as an identically-initialized dense model's KV
    decode (max_len divisible by the mesh axis)."""
    import jax
    import pytest
    from jax.sharding import Mesh
    from mxnet_tpu import parallel

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("sp",))
    dense = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          max_len=16, attn_type="dense")
    ring = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                         max_len=16, attn_type="ring")
    mx.random.seed(31)
    dense.initialize(mx.init.Xavier(), ctx=mx.cpu())
    ring.initialize(mx.init.Xavier(), ctx=mx.cpu())
    with parallel.sp_scope(mesh):      # ring's probe forward needs it
        copy_params(ring, dense)
    rs = np.random.RandomState(33)
    prompt = mx.nd.array(rs.randint(0, V, (2, 4)).astype("f"))
    want = dense.generate(prompt, 8, kv_cache=True).asnumpy()
    with parallel.sp_scope(mesh):
        got = ring.generate(prompt, 8, kv_cache=True).asnumpy()
    assert (got == want).all(), (got, want)
    # max_len not divisible by the axis -> loud error
    bad = TransformerLM(vocab=V, dim=32, num_layers=1, num_heads=4,
                        max_len=15, attn_type="ring")
    bad.initialize(mx.init.Xavier(), ctx=mx.cpu())
    with parallel.sp_scope(mesh), pytest.raises(ValueError):
        bad.generate(prompt, 2, kv_cache=True)


def test_sample_top_k_ties_and_validation():
    """top_k keeps exactly k survivors under ties (top_k=1 == argmax
    even with duplicated maxima); invalid top_k/top_p raise."""
    import pytest
    tied = mx.nd.array(np.array([[3.0, 3.0, 1.0, 0.0]], "f"))
    for _ in range(5):
        nxt = TransformerLM._sample(tied, 1.0, np.random.RandomState(0),
                                    top_k=1)
        assert nxt[0, 0] == 0.0          # first-occurrence max, = argmax
    with pytest.raises(ValueError):
        TransformerLM._sample(tied, 1.0, None, top_k=-1)
    with pytest.raises(ValueError):
        TransformerLM._sample(tied, 1.0, None, top_p=1.5)


def test_ulysses_kv_decode_matches_dense():
    """impl='ulysses' mha_decode_step (HEAD-sharded full-length caches,
    purely local attention per head shard) must match the dense decode
    step token-by-token, and a ulysses TransformerLM must generate
    kv_cache=True under an sp_scope with the same greedy tokens as an
    identically-initialized dense model."""
    import jax
    import pytest
    from jax.sharding import Mesh
    from mxnet_tpu import nd, parallel

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("sp",))
    rs = np.random.RandomState(43)
    Bq, H, Tmax, D = 2, 4, 8, 32          # H divisible by the axis
    dh = D // H
    qkv_seq = nd.array(rs.normal(0, 1, (Bq, Tmax, 3 * D)).astype("f"))
    kc_d = nd.zeros((Bq, H, Tmax, dh))
    vc_d = nd.zeros((Bq, H, Tmax, dh))
    kc_u = nd.zeros((Bq, H, Tmax, dh))
    vc_u = nd.zeros((Bq, H, Tmax, dh))
    for t in range(Tmax):
        step_qkv = nd.slice_axis(qkv_seq, axis=1, begin=t, end=t + 1)
        pos = nd.array([float(t)])
        od, kc_d, vc_d = nd.mha_decode_step(step_qkv, kc_d, vc_d, pos,
                                            num_heads=H)
        with parallel.sp_scope(mesh):
            ou, kc_u, vc_u = nd.mha_decode_step(step_qkv, kc_u, vc_u,
                                                pos, num_heads=H,
                                                impl="ulysses")
        assert_almost_equal(ou.asnumpy(), od.asnumpy(),
                            rtol=1e-4, atol=1e-5)
    assert_almost_equal(kc_u.asnumpy(), kc_d.asnumpy(), rtol=1e-5,
                        atol=1e-6)
    assert_almost_equal(vc_u.asnumpy(), vc_d.asnumpy(), rtol=1e-5,
                        atol=1e-6)

    # model level
    dense = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                          max_len=16, attn_type="dense")
    uly = TransformerLM(vocab=V, dim=32, num_layers=2, num_heads=4,
                        max_len=16, attn_type="ulysses")
    mx.random.seed(47)
    dense.initialize(mx.init.Xavier(), ctx=mx.cpu())
    uly.initialize(mx.init.Xavier(), ctx=mx.cpu())
    with parallel.sp_scope(mesh):
        copy_params(uly, dense)
    rs2 = np.random.RandomState(49)
    prompt = mx.nd.array(rs2.randint(0, V, (2, 4)).astype("f"))
    want = dense.generate(prompt, 8, kv_cache=True).asnumpy()
    with parallel.sp_scope(mesh):
        got = uly.generate(prompt, 8, kv_cache=True).asnumpy()
    assert (got == want).all(), (got, want)
    # heads not divisible by the axis -> loud error (3 heads, 4 devs)
    bad = TransformerLM(vocab=V, dim=33, num_layers=1, num_heads=3,
                        max_len=16, attn_type="ulysses")
    bad.initialize(mx.init.Xavier(), ctx=mx.cpu())
    with parallel.sp_scope(mesh), pytest.raises(ValueError):
        bad.generate(prompt, 2, kv_cache=True)


def test_sp_backward_after_scope_exit():
    """backward() issued AFTER the sp_scope exited must still work: the
    cached sp fwd/bwd jits re-enter their KEYED scope around every
    call, so lazy (re)traces never read the wrong ambient scope."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu import nd, parallel

    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("sp",))
    rs = np.random.RandomState(53)
    qkv = mx.nd.array(rs.normal(0, 1, (2, 16, 96)).astype("f"))
    qkv.attach_grad()
    with parallel.sp_scope(mesh):
        with autograd.record():
            out = nd._contrib_multihead_attention(qkv, num_heads=4,
                                                  impl="ring")
            loss = out.sum()
    loss.backward()                      # scope no longer active
    assert np.isfinite(qkv.grad.asnumpy()).all()


def test_beam_and_export_refuse_sp_models():
    """Beam search and the decode-step export are dense-cache paths:
    on sp-attention models they refuse loudly (allow_sp=False) even
    under an active scope."""
    import jax
    import pytest
    from jax.sharding import Mesh
    from mxnet_tpu import parallel

    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("sp",))
    net = make_net("ring", seed=14)
    prompt = mx.nd.array(np.zeros((1, 3), "f"))
    with parallel.sp_scope(mesh):
        with pytest.raises(NotImplementedError):
            net.beam_search(prompt, 2, beam=2)
        with pytest.raises(NotImplementedError):
            net.export_decode_step("/tmp/should_not_exist")
