"""Finite-difference gradient tier (parity: the reference op suite's
check_numeric_gradient usage across tests/python/unittest/test_operator.py)
— every analytic vjp in the registry family below is validated against
central differences on tiny shapes."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import check_numeric_gradient


def v(name="data"):
    return sym.Variable(name)


rs = np.random.RandomState(7)

CASES = [
    ("fc", sym.FullyConnected(v(), num_hidden=4, name="fc"),
     {"data": rs.randn(3, 5), "fc_weight": rs.randn(4, 5),
      "fc_bias": rs.randn(4)}),
    ("conv2d",
     sym.Convolution(v(), kernel=(3, 3), num_filter=2, pad=(1, 1),
                     name="cv"),
     {"data": rs.randn(1, 2, 5, 5), "cv_weight": rs.randn(2, 2, 3, 3),
      "cv_bias": rs.randn(2)}),
    ("deconv2d",
     sym.Deconvolution(v(), kernel=(2, 2), num_filter=2, stride=(2, 2),
                       name="dc"),
     {"data": rs.randn(1, 2, 3, 3), "dc_weight": rs.randn(2, 2, 2, 2)}),
    ("pool_max",
     sym.Pooling(v(), kernel=(2, 2), stride=(2, 2), pool_type="max"),
     {"data": rs.randn(1, 2, 4, 4)}),
    ("pool_avg",
     sym.Pooling(v(), kernel=(2, 2), stride=(1, 1), pool_type="avg",
                 pad=(1, 1)),
     {"data": rs.randn(1, 2, 4, 4)}),
    ("layernorm",
     sym.LayerNorm(v("data"), v("g"), v("b")),
     {"data": rs.randn(3, 6), "g": rs.rand(6) + 0.5, "b": rs.randn(6)}),
    # BlockGrad'd inputs are perturbed by the finite difference but have
    # zero analytic grad by design — check only the data path
    ("softmax_ce",
     0.0 - sym.sum(sym.log_softmax(v()) *
                   sym.BlockGrad(sym.softmax(v("t")))),
     {"data": rs.randn(3, 5), "t": rs.randn(3, 5)}, ["data"]),
    ("broadcast_chain",
     sym.broadcast_mul(sym.broadcast_add(v("a"), v("b")), v("a")),
     {"a": rs.randn(3, 1, 4), "b": rs.randn(1, 2, 4)}),
    ("reduce_mean", sym.mean(v(), axis=1, keepdims=True) * 3.0,
     {"data": rs.randn(4, 5)}),
    ("take_embed", sym.take(v("w"), sym.BlockGrad(sym.abs(v("i"))) * 2),
     {"w": rs.randn(7, 3), "i": rs.rand(4)}, ["w"]),
    ("batch_dot", sym.batch_dot(v("a"), v("b")),
     {"a": rs.randn(2, 3, 4), "b": rs.randn(2, 4, 2)}),
    ("mha",
     sym.multihead_attention(v(), num_heads=2, causal=True,
                             impl="dense"),
     {"data": rs.randn(1, 4, 12)}),
    ("tanh_chain", sym.tanh(v()) * sym.sigmoid(v()),
     {"data": rs.randn(3, 4)}),
    ("smooth_l1", sym.smooth_l1(v(), scalar=2.0),
     {"data": rs.randn(3, 4)}),
    ("transpose_reshape",
     sym.Reshape(sym.transpose(v(), axes=(1, 0, 2)), shape=(-1, 4)),
     {"data": rs.randn(2, 3, 4)}),
    ("upsample",
     sym.UpSampling(v(), scale=2, sample_type="nearest"),
     {"data": rs.randn(1, 2, 3, 3)}),
    ("slice_assign_grad",
     sym._slice_assign(v("a"), v("b"), begin=(1, 1), end=(3, 3)),
     {"a": rs.randn(4, 4), "b": rs.randn(2, 2)}),
    ("reshape_like_grad",
     sym.reshape_like(v("a"), sym.BlockGrad(v("b"))),
     {"a": rs.randn(2, 6), "b": rs.randn(3, 4)}, ["a"]),
]


CASES += [
    # round 4: vision/legacy backward paths that had no finite-diff net
    ("lrn", sym.LRN(v(), nsize=3, alpha=1e-2, beta=0.5),
     {"data": rs.randn(1, 4, 3, 3) * 0.5 + 1.0}),
    ("l2_normalization", sym.L2Normalization(v(), eps=1e-4),
     {"data": rs.randn(2, 3, 4) + 0.3}),
    ("instance_norm",
     sym.InstanceNorm(v("data"), v("g"), v("b"), eps=1e-3),
     {"data": rs.randn(2, 3, 4, 4), "g": rs.rand(3) + 0.5,
      "b": rs.randn(3)}),
    ("pad_reflect",
     sym.Pad(v(), mode="reflect", pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
     {"data": rs.randn(1, 2, 3, 3)}),
    ("sequence_reverse", sym.SequenceReverse(v()) * 2.0,
     {"data": rs.randn(3, 2, 4)}),
    ("bilinear_sampler",
     sym.BilinearSampler(v("data"), sym.BlockGrad(sym.tanh(v("grid")))
                         * 0.7),
     {"data": rs.randn(1, 2, 4, 4), "grid": rs.randn(1, 2, 3, 3)},
     ["data"]),
    ("spatial_transformer",
     sym.SpatialTransformer(v("data"), sym.BlockGrad(v("theta")),
                            target_shape=(3, 3),
                            transform_type="affine",
                            sampler_type="bilinear"),
     {"data": rs.randn(1, 2, 4, 4),
      "theta": np.array([[0.9, 0.05, 0.02, -0.04, 0.85, 0.01]])},
     ["data"]),
    ("swapaxis_crop",
     sym.Crop(sym.SwapAxis(v(), dim1=2, dim2=3), offset=(1, 1),
              h_w=(2, 2)),
     {"data": rs.randn(1, 2, 4, 4)}),
]


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_numeric_gradient(case):
    name, s, loc = case[0], case[1], case[2]
    grad_nodes = case[3] if len(case) > 3 else None
    loc = {k: val.astype(np.float32) for k, val in loc.items()}
    check_numeric_gradient(s, loc, numeric_eps=1e-3, rtol=2e-2, atol=2e-2,
                           grad_nodes=grad_nodes)


def test_rnn_op_numeric_gradient():
    """The fused RNN op's scan-based vjp against central differences
    (tiny LSTM, default zero states)."""
    rs2 = np.random.RandomState(11)
    T_, B_, I_, H_ = 3, 2, 3, 4
    nparams = 4 * H_ * I_ + 4 * H_ * H_ + 8 * H_
    s = sym.RNN(v("data"), v("par"), state_size=H_, num_layers=1,
                mode="lstm", use_default_state=True)
    loc = {"data": rs2.randn(T_, B_, I_).astype(np.float32),
           "par": (rs2.randn(nparams) * 0.3).astype(np.float32)}
    check_numeric_gradient(s, loc, numeric_eps=1e-2, rtol=5e-2, atol=5e-2)
