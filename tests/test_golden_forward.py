"""Golden-logit zoo gate (VERDICT r3 #2; parity:
tests/python/gpu/test_forward.py).

Each case rebuilds a model-zoo net from fixed seeds and compares its
logits against the committed fixture at 1e-4 — ANY numeric drift in
init, ops, or the gluon stack fails here.  Regenerate intentionally with
tools/make_golden.py.  The on-chip twin runs in
tools/run_tpu_consistency.py (looser tol for bf16 MXU matmuls).
"""
import numpy as np
import pytest

from mxnet_tpu.test_utils import (golden_fixture_path, golden_forward,
                                  golden_model_cases)

CASES = sorted(golden_model_cases())


@pytest.mark.parametrize("name", CASES)
def test_golden_logits(name):
    fixture = np.load(golden_fixture_path(name))["logits"]
    got = golden_forward(name)
    assert got.shape == fixture.shape
    np.testing.assert_allclose(got, fixture, rtol=1e-4, atol=1e-4)


def test_golden_is_deterministic():
    """Two rebuilds in one process produce identical logits (the fixture
    contract is meaningless without this)."""
    a = golden_forward("mobilenet0_25")
    b = golden_forward("mobilenet0_25")
    np.testing.assert_array_equal(a, b)
