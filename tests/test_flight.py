"""Flight recorder (ISSUE 8): ring wraparound/drops, trace-id
propagation across serving threads, Perfetto/Chrome-trace schema,
anomaly + SIGUSR2 auto-dump, MXNET_FLIGHT=0 no-op, sanitizer-clean
concurrent writers, exemplar -> timeline linkage.

Acceptance pinned here: a slow-request injection (faultinject
serving.dispatch delay) auto-produces a Perfetto-loadable dump whose
per-request spans (queue -> pad -> dispatch -> slice) share one
trace_id; the fused trainer step keeps the <=4-dispatch gate with the
recorder enabled.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faultinject as fi
from mxnet_tpu import serving, sym
from mxnet_tpu.base import unique_path
from mxnet_tpu.observability import flight, metrics as m, timeline

pytestmark = pytest.mark.flight

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_flight():
    """Each test gets an enabled recorder with a fresh ring and the
    default knobs back afterwards."""
    ring0, factor0, min_s0 = flight.RING, flight.SLOW_FACTOR, \
        flight.AUTO_DUMP_MIN_S
    flight.enable()
    flight.reset()
    yield
    flight.RING, flight.SLOW_FACTOR = ring0, factor0
    flight.AUTO_DUMP_MIN_S = min_s0
    flight.enable()
    flight.reset()


# -- helpers (serving fixture shared with test_serving idiom) ---------------

def _mlp_symbol(nin=8, nhid=16, nout=4):
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=nhid,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=nout, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _mlp_predictor(max_batch=8, **kw):
    net = _mlp_symbol()
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = net.infer_shape(data=(max_batch, 8))
    params = {}
    for n, s in zip(net.list_arguments(), arg_shapes):
        if n == "data" or n.endswith("_label"):
            continue
        params["arg:" + n] = mx.nd.array(rs.normal(0, 0.1, s).astype("f"))
    return serving.BucketedPredictor(net, params,
                                     {"data": (max_batch, 8)}, **kw)


def _spans(name=None):
    out = [r for _, r in flight.records()]
    return out if name is None else [r for r in out if r[0] == name]


def _wait_for(pred, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _dumps(d):
    """COMMITTED dump files only: atomic_write's same-dir tmp is
    transiently visible, and polling must never json.load a partial."""
    return sorted(n for n in os.listdir(str(d))
                  if n.endswith(".json") and ".tmp" not in n)


# -- ring basics -------------------------------------------------------------

def test_phase_span_records_fields():
    with flight.phase_span("unit_phase", cat="testcat", step=7,
                           labels={"k": "v"}):
        time.sleep(0.001)
    (rec,) = _spans("unit_phase")
    name, cat, t0, t1, step, trace_id, labels = rec
    assert cat == "testcat" and step == 7 and labels == {"k": "v"}
    assert t1 > t0 and (t1 - t0) >= 1e3  # >= 1ms in microseconds
    assert trace_id is None


def test_ring_wraparound_and_drop_count():
    flight.configure(ring=8)
    for i in range(20):
        flight.record("wrap_phase", "t", float(i), float(i) + 0.5)
    st = flight.stats()
    assert st["written"] == 20 and st["drops"] == 12
    assert st["records"] == 8
    kept = _spans("wrap_phase")
    assert len(kept) == 8
    # the ring keeps the NEWEST 8 records
    assert sorted(r[2] for r in kept) == [float(i) for i in range(12, 20)]


def test_disabled_is_noop():
    flight.disable()
    with flight.phase_span("never_recorded"):
        pass
    flight.record("never_recorded", "t", 0.0, 1.0)
    flight.note("never_recorded", 100.0)  # no EWMA, no dump
    assert flight.stats()["records"] == 0
    assert flight.stats()["enabled"] is False
    assert flight.watch_state() == {}


def test_flight_env_off_subprocess(tmp_path):
    """MXNET_FLIGHT=0 at import: hooks reduce to one boolean test and
    record nothing — and a later enable() restores full function,
    including the SIGUSR2 handler the import-time path skipped."""
    code = (
        "import os, signal, time\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.observability import flight\n"
        "assert flight.ENABLED is False\n"
        "with flight.phase_span('x'):\n"
        "    pass\n"
        "assert flight.stats()['records'] == 0\n"
        "flight.enable()   # must also arm kill -USR2 retroactively\n"
        "with flight.phase_span('late_phase'):\n"
        "    pass\n"
        "os.kill(os.getpid(), signal.SIGUSR2)\n"
        "d = os.environ['MXNET_FLIGHT_DIR']\n"
        "for _ in range(100):\n"
        "    if [n for n in os.listdir(d)\n"
        "            if n.endswith('.json') and '.tmp' not in n]:\n"
        "        break\n"
        "    time.sleep(0.05)\n"
        "else:\n"
        "    raise AssertionError('late-enabled SIGUSR2 never dumped')\n"
        "print('OK')\n")
    env = dict(os.environ, MXNET_FLIGHT="0", JAX_PLATFORMS="cpu",
               MXNET_FLIGHT_DIR=str(tmp_path))
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout[-500:], out.stderr[-2000:])


def test_reset_isolates_other_threads_segments():
    done = threading.Event()
    go_again = threading.Event()

    def worker():
        with flight.phase_span("thread_phase"):
            pass
        done.set()
        go_again.wait(5)
        with flight.phase_span("thread_phase_2"):
            pass

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert done.wait(5)
    assert len(_spans("thread_phase")) == 1
    flight.reset()
    assert flight.stats()["records"] == 0
    # the worker's stale thread-local segment must NOT resurrect into
    # the cleared registry — a new epoch gives it a fresh segment
    go_again.set()
    t.join(5)
    assert len(_spans("thread_phase")) == 0
    assert len(_spans("thread_phase_2")) == 1


def test_dead_thread_segments_bounded():
    """Thread churn (a prefetcher per epoch, pool restarts) must not
    grow the segment registry forever: dead-thread segments are pruned
    past MAX_DEAD_SEGMENTS at registration, recent ones kept for
    post-mortem."""
    flight.configure(ring=4)

    def spin(i):
        flight.record("churn_phase", "t", float(i), float(i) + 1.0)

    n = flight.MAX_DEAD_SEGMENTS + 12
    for i in range(n):
        t = threading.Thread(target=spin, args=(i,))
        t.start()
        t.join(5)
    st = flight.stats()
    # every registration after the cap pruned the oldest dead segments
    assert st["segments"] <= flight.MAX_DEAD_SEGMENTS + 2, st
    # the NEWEST dead threads' records survive for post-mortem
    kept = sorted(r[2] for r in _spans("churn_phase"))
    assert kept and kept[-1] == float(n - 1)


# -- chrome trace schema -----------------------------------------------------

def test_dump_chrome_trace_schema(tmp_path):
    with flight.trace_scope("tid-1"):
        with flight.phase_span("schema_phase", cat="c", step=3):
            pass
    path = flight.dump(path=str(tmp_path / "t.json"))
    with open(path) as f:
        doc = json.load(f)   # loadable = valid JSON
    assert "traceEvents" in doc and doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    ms = [e for e in evs if e.get("ph") == "M"]
    assert xs and ms
    for e in xs:
        # the trace-event fields Perfetto requires for a complete event
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}, e
    ev = next(e for e in xs if e["name"] == "schema_phase")
    assert ev["cat"] == "c"
    assert ev["args"]["step"] == 3 and ev["args"]["trace_id"] == "tid-1"
    # thread_name metadata names the row
    assert any(e["name"] == "thread_name" and "name" in e["args"]
               for e in ms)
    # complete events are time-sorted (one coherent timeline)
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)


def test_dump_merges_profiler_events(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "prof.json"))
    mx.profiler.set_state("run")
    try:
        with mx.observability.trace_span("prof_side_span"):
            with flight.phase_span("flight_side_span"):
                pass
    finally:
        mx.profiler.set_state("stop")
    path = flight.dump(path=str(tmp_path / "merged.json"))
    with open(path) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "prof_side_span" in names and "flight_side_span" in names


def test_dump_default_dir_and_unique_name(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_DIR", str(tmp_path / "dumps"))
    clock = lambda: 1700000000.0  # noqa: E731 — injected, deterministic
    p1 = flight.dump(clock=clock)
    p2 = flight.dump(clock=clock)
    assert os.path.dirname(p1) == str(tmp_path / "dumps")
    assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)
    assert m.FLIGHT_DUMPS.get(reason="manual") >= 2.0


def test_unique_path_collision_policy(tmp_path):
    """profiler + flight share ONE filename policy: timestamped via an
    injected clock, collision -> .N suffix (no ambient-time races)."""
    clock = lambda: 1700000000.0  # noqa: E731
    p1 = unique_path(str(tmp_path), "flight", ".json", clock=clock)
    open(p1, "w").close()
    p2 = unique_path(str(tmp_path), "flight", ".json", clock=clock)
    assert p2 != p1 and p2.endswith(".1.json")
    open(p2, "w").close()
    p3 = unique_path(str(tmp_path), "flight", ".json", clock=clock)
    assert p3.endswith(".2.json")
    assert "20231114" in os.path.basename(p1)  # stamp comes from clock


def test_dump_profile_is_atomic_via_base(tmp_path):
    """dump_profile routes through base.atomic_write (the shared
    policy): the committed file is valid JSON, no .tmp residue."""
    fname = str(tmp_path / "prof.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.set_state("run")
    with mx.observability.trace_span("x"):
        pass
    mx.profiler.dump_profile()
    with open(fname) as f:
        assert "traceEvents" in json.load(f)
    assert not [p for p in os.listdir(tmp_path) if ".tmp" in p]


# -- tracing satellite: depth accounting + paused-profiler fallback ----------

def test_trace_span_depth_exception_safe(tmp_path):
    from mxnet_tpu.observability import tracing
    mx.profiler.set_config(filename=str(tmp_path / "p.json"))
    mx.profiler.set_state("run")
    try:
        with pytest.raises(RuntimeError):
            with mx.observability.trace_span("outer"):
                with mx.observability.trace_span("inner"):
                    raise RuntimeError("boom")
        # depth restored through BOTH unwinds, events still recorded
        assert tracing._depth() == 0
        names = [e["name"] for e in mx.profiler._events]
        assert names.count("inner") == 1 and names.count("outer") == 1
        inner = next(e for e in mx.profiler._events
                     if e["name"] == "inner")
        outer = next(e for e in mx.profiler._events
                     if e["name"] == "outer")
        # nesting invariant: inner's range inside outer's
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    finally:
        mx.profiler.set_state("stop")


def test_step_span_monotonic_fallback_when_paused(tmp_path):
    """While the profiler is PAUSED, step_span still lands a correctly
    ordered flight record (same perf_counter clock) and adds nothing to
    the suppressed profiler buffer — the two timelines cannot disagree
    on t0/t1 ordering across a pause/resume cycle."""
    mx.profiler.set_config(filename=str(tmp_path / "p.json"))
    mx.profiler.set_state("run")
    try:
        with mx.observability.step_span(1):
            pass
        mx.profiler.pause()
        with mx.observability.step_span(2):
            pass
        mx.profiler.resume()
        with mx.observability.step_span(3):
            pass
    finally:
        mx.profiler.set_state("stop")
    prof_steps = [e["args"]["step"] for e in mx.profiler._events
                  if e["cat"] == "step"]
    assert prof_steps == [1, 3]          # paused step suppressed (parity)
    fl = _spans("train_step")
    assert [r[4] for r in fl] == [1, 2, 3]   # flight saw all three
    t0s = [r[2] for r in fl]
    assert t0s == sorted(t0s)            # monotonic ordering held
    # cross-timeline ordering: step 3's profiler ts >= step 2's flight t1
    step3 = next(e for e in mx.profiler._events
                 if e["cat"] == "step" and e["args"]["step"] == 3)
    assert step3["ts"] >= fl[1][3]


# -- trainer / fit integration ----------------------------------------------

def _one_gluon_step(net=None):
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    rs = np.random.RandomState(0)
    if net is None:
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="relu"))
            net.add(nn.Dense(1))
        net.hybridize()
        net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01}, kvstore="tpu_sync",
                            update_on_kvstore=False)
    x = mx.nd.array(rs.normal(0, 1, (4, 8)).astype("f"))
    y = mx.nd.array(rs.normal(0, 1, (4, 1)).astype("f"))
    loss_fn = gluon.loss.L2Loss()
    for _ in range(3):
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(4)
    return trainer


def test_trainer_step_phases_recorded():
    _one_gluon_step()
    steps = _spans("trainer_step")
    assert len(steps) == 3
    assert [r[4] for r in steps] == [0, 1, 2]      # step ids
    assert len(_spans("allreduce")) == 3
    assert len(_spans("fused_update")) == 3
    # sub-phases nest inside their step's window and share its step id
    s0 = steps[0]
    ar0 = _spans("allreduce")[0]
    assert s0[2] <= ar0[2] and ar0[3] <= s0[3] and ar0[4] == 0
    # watched: trainer_step feeds the watchdog EWMA
    assert flight.watch_state()["trainer_step"]["count"] == 3


@pytest.mark.perf_smoke
def test_fused_step_dispatch_gate_with_recorder_enabled():
    """Acceptance: the recorder is ON (default) and the fused trainer
    step still fits the <=4-dispatch budget — instrumentation must
    never become the overhead (or the dispatches) it measures."""
    assert flight.ENABLED
    from mxnet_tpu import observability as obs
    # steady-state: one net/trainer, warm, then measure per-step deltas
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    rs = np.random.RandomState(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(1))
    net.hybridize()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01}, kvstore="tpu_sync",
                            update_on_kvstore=False)
    x = mx.nd.array(rs.normal(0, 1, (4, 8)).astype("f"))
    y = mx.nd.array(rs.normal(0, 1, (4, 1)).astype("f"))
    loss_fn = gluon.loss.L2Loss()

    def step():
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(4)

    for _ in range(3):
        step()
    c0 = obs.dispatch_counts()
    for _ in range(3):
        step()
    c1 = obs.dispatch_counts()
    per = (c1["total"] - c0["total"]) / 3
    assert per <= 4.0, (per, c0, c1)
    assert m.TRAINER_STEP_DISPATCHES.get() <= 2.0


# -- serving: trace ids end to end -------------------------------------------

def test_trace_id_propagates_across_microbatcher_threads():
    pred = _mlp_predictor().warmup()
    with serving.MicroBatcher(pred, max_wait_ms=0) as mb:
        fut = mb.submit(data=np.zeros((2, 8), np.float32))
        fut.result(timeout=10)
    waits = _spans("serve_queue_wait")
    assert len(waits) == 1
    tid = waits[0][5]
    assert tid is not None
    # the group phases ran on the DISPATCHER thread; the request's id
    # reached them through trace_scope
    for phase in ("serve_submit", "serve_stack", "serve_pad",
                  "serve_dispatch", "serve_slice"):
        recs = _spans(phase)
        assert recs, phase
        assert any(r[5] is not None and tid in r[5] for r in recs), \
            (phase, tid, recs)
    # serve_submit ran on the CALLER thread, serve_dispatch on the
    # dispatcher — same trace id across two segments/threads
    segs = {id(s) for s, r in flight.records()
            if r[0] == "serve_submit"}
    dsegs = {id(s) for s, r in flight.records()
             if r[0] == "serve_dispatch"}
    assert segs and dsegs and segs != dsegs


def test_coalesced_group_ids_joined():
    pred = _mlp_predictor().warmup()
    with serving.MicroBatcher(pred, max_wait_ms=40, max_batch=8) as mb:
        f1 = mb.submit(data=np.zeros((2, 8), np.float32))
        f2 = mb.submit(data=np.ones((2, 8), np.float32))
        f1.result(timeout=10), f2.result(timeout=10)
    waits = _spans("serve_queue_wait")
    ids = {r[5] for r in waits}
    assert len(ids) == 2
    disp = _spans("serve_dispatch")
    # both requests' ids joinable against the group dispatch span
    joined = ",".join(sorted(i for r in disp for i in (r[5] or "").split(",")))
    for i in ids:
        assert i in joined, (i, disp)


def test_resilient_server_admission_and_exemplars():
    pred = _mlp_predictor().warmup()
    m.SERVE_LATENCY_SECONDS.reset()
    with serving.ResilientServer(pred, max_wait_ms=0) as srv:
        srv.predict(data=np.zeros((2, 8), np.float32))
    adm = _spans("serve_admission")
    assert len(adm) == 1 and adm[0][5] is not None
    tid = adm[0][5]
    waits = _spans("serve_queue_wait")
    assert waits and waits[0][5] == tid
    # exemplar: some latency bucket carries this request's trace id
    ex = m.SERVE_LATENCY_SECONDS.exemplars()
    assert any(v["trace_id"] == tid for v in ex.values()), (tid, ex)
    snap = mx.observability.snapshot()
    assert snap["serving"]["latency_exemplars"] == ex
    assert snap["flight"]["enabled"] is True
    assert "serve_dispatch" in snap["flight"]["phases"]


# -- watchdog / auto-dump ----------------------------------------------------

def test_slow_phase_anomaly_autodump(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_DIR", str(tmp_path))
    flight.SLOW_FACTOR = 3.0
    flight.AUTO_DUMP_MIN_S = 0.0
    for _ in range(6):
        flight.note("unit_step", 0.010)
    assert not _dumps(tmp_path)          # warmed, nothing anomalous
    flight.note("unit_step", 0.200)      # 20x the EWMA
    assert _wait_for(lambda: _dumps(tmp_path))
    (name,) = _dumps(tmp_path)
    with open(tmp_path / name) as f:
        doc = json.load(f)
    assert doc["metadata"]["reason"] == "anomaly"
    assert doc["metadata"]["anomaly"]["phase"] == "unit_step"
    assert m.FLIGHT_DUMPS.get(reason="anomaly") >= 1.0
    st = flight.stats()
    assert st["last_anomaly"]["phase"] == "unit_step"


def test_autodump_rate_limited(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_DIR", str(tmp_path))
    flight.SLOW_FACTOR = 3.0
    flight.AUTO_DUMP_MIN_S = 3600.0
    for _ in range(6):
        flight.note("rl_step", 0.010)
    flight.note("rl_step", 0.500)
    assert _wait_for(lambda: _dumps(tmp_path))
    n1 = len(_dumps(tmp_path))
    for _ in range(6):
        flight.note("rl_step", 0.500)    # would re-trigger, rate-limited
    time.sleep(0.1)
    assert len(_dumps(tmp_path)) == n1


@pytest.mark.chaos
def test_slow_request_injection_autoproduces_linked_dump(tmp_path,
                                                         monkeypatch):
    """THE acceptance drill: a faultinject serving.dispatch delay makes
    one request slow; the watchdog auto-dumps a Perfetto-loadable
    timeline in which that request's queue/pad/dispatch/slice spans
    share one trace_id."""
    monkeypatch.setenv("MXNET_FLIGHT_DIR", str(tmp_path))
    flight.SLOW_FACTOR = 4.0
    flight.AUTO_DUMP_MIN_S = 0.0
    pred = _mlp_predictor().warmup()
    with serving.MicroBatcher(pred, max_wait_ms=0) as mb:
        for _ in range(8):   # warm the serve_request EWMA
            mb.submit(data=np.zeros((2, 8), np.float32)).result(timeout=10)
        assert not _dumps(tmp_path)
        with fi.active(fi.FaultPlan().add("serving.dispatch", "delay",
                                          delay_s=0.25)):
            mb.submit(data=np.zeros((2, 8), np.float32)).result(timeout=10)
    assert _wait_for(lambda: _dumps(tmp_path)), \
        "slow request did not auto-dump"
    newest = max((tmp_path / n for n in _dumps(tmp_path)),
                 key=os.path.getmtime)
    with open(newest) as f:
        doc = json.load(f)
    assert doc["metadata"]["anomaly"]["phase"] == "serve_request"
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    # find the slow dispatch, take its trace_id, demand the full chain
    slow = max((e for e in evs if e["name"] == "serve_dispatch"),
               key=lambda e: e["dur"])
    tid = slow["args"]["trace_id"].split(",")[0]
    chain = {"serve_queue_wait", "serve_pad", "serve_dispatch",
             "serve_slice"}
    got = {e["name"] for e in evs
           if tid in (e.get("args", {}).get("trace_id") or "")}
    assert chain <= got, (tid, sorted(got))
    assert slow["dur"] >= 0.2 * 1e6      # the injected 250ms is visible


# -- SIGUSR2 -----------------------------------------------------------------

@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="platform has no SIGUSR2")
def test_sigusr2_dump_in_subprocess(tmp_path):
    code = (
        "import os, signal, time, json\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.observability import flight\n"
        "with flight.phase_span('sig_phase'):\n"
        "    pass\n"
        "os.kill(os.getpid(), signal.SIGUSR2)\n"
        "for _ in range(100):\n"
        "    names = [n for n in os.listdir(os.environ['MXNET_FLIGHT_DIR'])\n"
        "             if n.endswith('.json') and '.tmp' not in n]\n"
        "    if names: break\n"
        "    time.sleep(0.05)\n"
        "doc = json.load(open(os.path.join(\n"
        "    os.environ['MXNET_FLIGHT_DIR'], names[0])))\n"
        "assert doc['metadata']['reason'] == 'signal', doc\n"
        "assert any(e['name'] == 'sig_phase'\n"
        "           for e in doc['traceEvents']), doc\n"
        "print('OK')\n")
    env = dict(os.environ, MXNET_FLIGHT_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout[-1000:], out.stderr[-2000:])


# -- concurrency -------------------------------------------------------------

def test_sanitizer_clean_concurrent_writers():
    """The drill the 'lock-cheap ring writes' claim must survive:
    N writer threads + a concurrent dumper/summarizer under
    MXNET_SANITIZE=1 — no lock-order violations, no lost segments,
    consistent written counts."""
    from mxnet_tpu.analysis import sanitizer as san
    san.reset()
    san.enable()
    try:
        flight.configure(ring=64)   # rebuilds flight locks as tracked
        per_thread, n_threads = 200, 6
        errs = []

        def writer(k):
            try:
                for i in range(per_thread):
                    with flight.phase_span("conc_phase", cat="t",
                                           step=i, watch=(i % 10 == 0)):
                        pass
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def reader():
            try:
                for _ in range(20):
                    flight.summary()
                    flight.stats()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=writer, args=(k,))
              for k in range(n_threads)] + [threading.Thread(target=reader)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs, errs
        assert san.violations() == [], san.violations()
        st = flight.stats()
        assert st["written"] == per_thread * n_threads
        assert st["segments"] == n_threads   # reader wrote nothing
        assert st["drops"] == n_threads * (per_thread - 64)
    finally:
        san.disable()
        san.reset()
        flight.configure(ring=int(mx.base.getenv("MXNET_FLIGHT_RING",
                                                 4096)))


# -- snapshot / summary schema ----------------------------------------------

def test_snapshot_flight_schema():
    with flight.phase_span("snap_phase", step=1):
        pass
    blk = mx.observability.snapshot()["flight"]
    assert set(blk) >= {"enabled", "ring", "records", "written", "drops",
                        "segments", "dumps", "phases", "watch"}
    ph = blk["phases"]["snap_phase"]
    assert set(ph) >= {"count", "total_ms", "p50_ms", "p99_ms", "max_ms",
                       "slowest"}
    assert ph["count"] == 1 and ph["slowest"][0]["step"] == 1
    json.dumps(blk)   # JSON-able end to end


def test_summary_percentiles_and_slowest():
    for i in range(100):
        flight.record("pctl_phase", "t", 0.0, float(i + 1) * 1e3)
    s = flight.summary(top=2)["pctl_phase"]
    assert s["count"] == 100
    assert 45.0 <= s["p50_ms"] <= 55.0
    assert 95.0 <= s["p99_ms"] <= 100.0
    assert s["max_ms"] == 100.0
    assert [r["dur_ms"] for r in s["slowest"]] == [100.0, 99.0]


def test_phase_name_cardinality_rule():
    """The new graft-lint facet: a dynamically built phase name is a
    finding; literal names pass."""
    from mxnet_tpu.analysis.checkers import MetricsHygieneChecker
    from mxnet_tpu.analysis.core import FileCtx
    import ast as _ast
    bad = ("from mxnet_tpu.observability import flight\n"
           "def f(key, prof):\n"
           "    with flight.phase_span(f'phase_{key}'):\n"
           "        pass\n"
           "    flight.record('ok_literal', 't', 0, 1)\n"
           "    with flight.phase_span('fine'):\n"
           "        pass\n"
           "    with prof.phase_span('p_' + key):\n"   # any alias/base
           "        pass\n"
           "    fl = flight\n"
           "    fl.record(key.format(), 't', 0, 1)\n")
    ctx = FileCtx("x.py", "x.py", bad, _ast.parse(bad))
    findings = MetricsHygieneChecker().check_file(ctx)
    assert len(findings) == 3, findings
    assert all("phase name" in f.message for f in findings)
    assert sorted(f.line for f in findings) == [3, 8, 11]
