"""Deterministic fault injection — make failure behavior *testable*.

The robustness claims this codebase makes (serving degrades to bounded
p99 + typed rejections, checkpoints retry transient IO and survive
corruption, hot reload keeps serving old weights) are only claims until
a test can FORCE each failure at will.  This module is the process-wide
switchboard for that: a ``FaultPlan`` maps named injection *sites* to
deterministic fault rules (raise / delay / corrupt, with exact
occurrence windows — no randomness, so a chaos test that passes once
passes always), and the runtime calls ``fire(site)`` at each wired
site.  With no plan installed ``fire`` is one module-global ``is None``
test — the same never-become-the-overhead rule the metrics layer
follows.

Wired sites (each degrades as documented in
docs/serving_resilience.md):

  ======================  ==================================================
  ``serving.dispatch``    ``BucketedPredictor._dispatch`` — every compiled
                          bucket launch (delay = slow model, raise = failed
                          dispatch routed to the caller/future)
  ``serving.batcher``     ``MicroBatcher`` dispatcher thread, before each
                          group dispatch (raise = worker death; pending
                          futures must fail typed, never hang)
  ``serving.hot_reload``  ``BucketedPredictor.hot_reload`` entry (raise =
                          failed weight swap; auto-reload keeps old weights
                          and counts ``mxnet_serve_reload_failures_total``)
  ``serving.decode_step``  ``DecodeEngine.step`` — continuous-batching
                          decode, fired inside the ``decode_step``
                          flight span BEFORE the donated dispatch
                          (raise = a failed step mid-generation with
                          every sequence's state intact, so a retried
                          ``step()`` resumes bitwise; delay = a slow
                          step feeding the EDF per-step EWMA, so
                          deadline shedding tightens under injected
                          slowness) — docs/decode_serving.md
  ``serving.evict``       ``ModelRegistry`` LRU eviction AND
                          ``DecodeEngine.release_kv_pages`` (KV-page
                          arbiter reclaim), once per reclaim
                          (bucket, model, or a sequence's KV pages)
                          BEFORE any state is dropped —
                          delay = slow eviction under churn, raise = a
                          failed eviction the budgeter must skip (the
                          victim stays resident; admission degrades to a
                          typed ``ModelUnavailable`` when nothing else
                          can be freed).  Lets the chaos suite drive
                          deterministic eviction churn
                          (docs/multi_model.md)
  ``checkpoint.io``       ``CheckpointManager`` write attempts (raise
                          ``OSError`` to exercise the retry path, the
                          default ``InjectedFault`` to exhaust it) plus a
                          post-write ``corrupt`` hook that flips bytes in a
                          committed shard (restore must skip it via CRC)
  ``memory.oom``          the dispatch chokepoints guarded by
                          ``memory.oom_guard`` (executor fused step,
                          fused optimizer update, serving dispatch) — a
                          ``raise`` rule is a synthetic RESOURCE_EXHAUSTED
                          (``is_oom`` matches the site name), so the OOM
                          post-mortem (catch → ledger+ring dump → typed
                          ``DeviceMemoryError``) is chaos-testable with no
                          real HBM pressure
  ``trainer.step``        every Gluon training step — ``Trainer._step``
                          on the fused/legacy paths AND
                          ``WholeStepCompiler._run`` on the whole-step
                          path, exactly once per step (raise = failed
                          step the ``TrainingSupervisor`` classifies and
                          retries; delay = slow step that feeds the stall
                          watchdog EWMA) — docs/training_resilience.md
  ``data.batch``          ``AsyncPrefetcher`` worker, before each source
                          read (raise ``OSError`` = transient IO the
                          worker respawns once over; raise
                          ``DataCorruptionError`` = corrupt record the
                          ``MXNET_DATA_SKIP_BUDGET`` consumes)
  ``kvstore.allreduce``   ``KVStore.allreduce`` entry — the fused
                          Trainer's bucketed gradient reduce (raise =
                          failed collective; whole-step mode inlines the
                          reduce into the donated program, so this site
                          only fires on the fused/legacy paths)
  ``kvstore.sparse_allreduce``  ``KVStore.allreduce_rowsparse`` entry —
                          the row-sparse (ids, rows) gradient reduce of
                          sharded embeddings (ISSUE 20), fired BEFORE
                          any reduce work so an injected raise models a
                          failed sparse collective with per-row
                          optimizer state untouched; the
                          ``TrainingSupervisor`` restores through the
                          snapshot window and the retry is bitwise
                          (whole-step mode inlines the sparse reduce
                          into the donated program, like the dense site)
  ``device.unavailable``  the training dispatch chokepoints
                          (``WholeStepCompiler._dispatch``, the fused
                          update) — a ``raise`` rule defaults to the
                          typed ``DeviceUnavailableError`` (classified
                          transient), modeling a dropped TPU tunnel with
                          no real device loss
  ==================================================================

Configuration is API- or env-driven::

    plan = faultinject.FaultPlan()
    plan.add("serving.dispatch", "delay", delay_s=0.05)
    plan.add("checkpoint.io", "raise", exc=OSError, times=2)
    with faultinject.active(plan):
        ...  # chaos test body

    MXNET_FAULT_PLAN="serving.dispatch:delay:0.05;checkpoint.io:raise:OSError:2"

The env form is parsed at import (and by ``install_from_env()``), so a
subprocess chaos drill needs no code changes.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from .base import MXNetError
from .observability import metrics as _metrics
from .resilience import DataCorruptionError, DeviceUnavailableError

__all__ = ["InjectedFault", "FaultRule", "FaultPlan", "parse_plan",
           "install", "install_from_env", "clear", "active", "plan",
           "fire", "SITES", "ENV_VAR"]

log = logging.getLogger(__name__)

ENV_VAR = "MXNET_FAULT_PLAN"

#: the named sites the runtime has wired (fire() accepts any name — new
#: sites need no registration — but these are the documented ones)
SITES = ("serving.dispatch", "serving.batcher", "serving.hot_reload",
         "serving.evict", "serving.decode_step", "checkpoint.io",
         "memory.oom", "trainer.step", "data.batch",
         "kvstore.allreduce", "kvstore.sparse_allreduce",
         "device.unavailable")

_MODES = ("raise", "delay", "corrupt")


class InjectedFault(MXNetError):
    """The default exception a ``raise`` rule throws — typed, so tests
    and operators can tell an injected failure from an organic one."""


# exception classes the env syntax may name.  OSError is the important
# one: the checkpoint retry loop only retries OSError/IOError, so
# "checkpoint.io:raise:OSError:2" exercises retry-and-recover while the
# default InjectedFault exhausts straight to a CheckpointError.
_EXC_TYPES: Dict[str, type] = {
    "InjectedFault": InjectedFault,
    "MXNetError": MXNetError,
    "OSError": OSError,
    "IOError": IOError,
    "RuntimeError": RuntimeError,
    "TimeoutError": TimeoutError,
    # the training-resilience taxonomy (mxnet_tpu.resilience): a
    # transient device loss and a corrupt input record, so a chaos plan
    # can drive the supervisor retry and the data skip budget by name
    "DeviceUnavailableError": DeviceUnavailableError,
    "DataCorruptionError": DataCorruptionError,
}


class FaultRule:
    """One deterministic fault at one site.

    Parameters
    ----------
    site : str
        Injection-site name (see ``SITES``).
    mode : str
        ``"raise"`` | ``"delay"`` | ``"corrupt"``.
    delay_s : float
        Sleep duration for ``delay`` rules.
    exc : type
        Exception class for ``raise`` rules (default ``InjectedFault``).
    message : str, optional
        Exception message for ``raise`` rules.
    times : int, optional
        Fire on at most this many matching ``fire()`` calls (None =
        every call).
    after : int
        Skip the first ``after`` matching calls (fire on calls
        ``after .. after+times-1``) — lets a plan hit exactly the Nth
        dispatch.
    """

    def __init__(self, site: str, mode: str, delay_s: float = 0.0,
                 exc: type = InjectedFault, message: Optional[str] = None,
                 times: Optional[int] = None, after: int = 0):
        if mode not in _MODES:
            raise MXNetError(f"fault mode must be one of {_MODES}, "
                             f"got {mode!r}")
        if times is not None and times < 1:
            raise MXNetError(f"times must be >= 1 (or None), got {times}")
        if after < 0 or delay_s < 0:
            raise MXNetError("after/delay_s must be >= 0")
        self.site = str(site)
        self.mode = mode
        self.delay_s = float(delay_s)
        if exc is InjectedFault and self.site == "device.unavailable":
            # the site's whole point is modeling a transient device
            # loss — default its raise rules to the typed error the
            # resilience classifier maps to "transient"
            exc = DeviceUnavailableError
        self.exc = exc
        self.message = message
        self.times = times
        self.after = int(after)
        self.seen = 0   # matching fire() calls observed
        self.fired = 0  # times this rule actually acted

    def _should_fire(self) -> bool:
        """Advance the occurrence window.  Caller holds the plan lock."""
        idx = self.seen
        self.seen += 1
        if idx < self.after:
            return False
        if self.times is not None and idx >= self.after + self.times:
            return False
        self.fired += 1
        return True

    def __repr__(self):
        win = f"[{self.after}:" + (
            f"{self.after + self.times}]" if self.times is not None else "]")
        return (f"FaultRule({self.site}:{self.mode} {win} "
                f"fired={self.fired})")


class FaultPlan:
    """An ordered set of ``FaultRule``s; install process-wide with
    ``faultinject.install(plan)`` / ``with faultinject.active(plan):``."""

    def __init__(self):
        self._rules: List[FaultRule] = []
        from .analysis.sanitizer import make_lock
        self._lock = make_lock("faultinject.plan")

    def add(self, site: str, mode: str, **kw) -> "FaultPlan":
        """Append a rule (chainable): ``plan.add("serving.dispatch",
        "delay", delay_s=0.05).add("checkpoint.io", "raise",
        exc=OSError, times=2)``."""
        with self._lock:
            self._rules.append(FaultRule(site, mode, **kw))
        return self

    def rules(self, site: Optional[str] = None) -> List[FaultRule]:
        with self._lock:
            return [r for r in self._rules
                    if site is None or r.site == site]

    def stats(self) -> Dict[str, int]:
        """Per-site fired counts — chaos tests assert on these."""
        out: Dict[str, int] = {}
        with self._lock:
            for r in self._rules:
                out[r.site] = out.get(r.site, 0) + r.fired
        return out

    def reset(self) -> None:
        """Zero every rule's occurrence window (reuse one plan across
        test cases)."""
        with self._lock:
            for r in self._rules:
                r.seen = r.fired = 0

    # -- the injection hook --------------------------------------------------
    def _fire(self, site: str, only: Optional[str],
              corrupt: Optional[Callable[[], None]], ctx: dict) -> None:
        # decide under the lock (deterministic windows even with
        # concurrent fire()s), act outside it (a delay rule must not
        # serialize unrelated sites)
        firing: List[FaultRule] = []
        with self._lock:
            for r in self._rules:
                if r.site != site or (only is not None and r.mode != only):
                    continue
                if r.mode == "corrupt" and corrupt is None:
                    # corrupt rules act only at call points that offer
                    # a corruption hook — a hook-less fire() at the same
                    # site must not consume the occurrence window
                    continue
                if r._should_fire():
                    firing.append(r)
        for r in firing:
            if _metrics.ENABLED:
                _metrics.FAULTS_INJECTED.inc(site=site, mode=r.mode)
            log.warning("faultinject: %s at %s %s", r.mode, site,
                        ctx if ctx else "")
            if r.mode == "delay":
                time.sleep(r.delay_s)
            elif r.mode == "corrupt":
                if corrupt is not None:
                    corrupt()
            else:  # raise
                msg = r.message or (
                    f"injected fault at {site} "
                    f"(occurrence {r.fired - 1 + r.after})")
                raise r.exc(msg)


# ---------------------------------------------------------------------------
# process-wide active plan
# ---------------------------------------------------------------------------
_ACTIVE: Optional[FaultPlan] = None


def fire(site: str, only: Optional[str] = None,
         corrupt: Optional[Callable[[], None]] = None, **ctx) -> None:
    """The runtime-side hook: no-op (one global read) unless a plan is
    installed.  ``only`` restricts which rule modes may act at this call
    point (the checkpoint writer fires ``only="corrupt"`` AFTER the
    commit so a raise rule cannot double-fire); ``corrupt`` is the
    call-site-supplied mutator a corrupt rule invokes."""
    plan_ = _ACTIVE
    if plan_ is None:
        return
    plan_._fire(site, only, corrupt, ctx)


def install(plan_: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan_`` process-wide (None clears).  Returns the
    previously active plan."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan_
    return prev


def clear() -> None:
    install(None)


def plan() -> Optional[FaultPlan]:
    """The currently active plan (None = fault injection off)."""
    return _ACTIVE


@contextmanager
def active(plan_: FaultPlan):
    """Scope a plan to a with-block — the chaos-test idiom.  Restores
    whatever was active before (usually nothing) on exit, even when the
    body raises."""
    prev = install(plan_)
    try:
        yield plan_
    finally:
        install(prev)


# ---------------------------------------------------------------------------
# env-driven configuration
# ---------------------------------------------------------------------------
def parse_plan(spec: str) -> FaultPlan:
    """Parse the ``MXNET_FAULT_PLAN`` syntax: rules separated by ``;``
    (or ``,``), each ``site:mode[:arg][:times[:after]]``::

        serving.dispatch:delay:0.05        # 50 ms delay, every dispatch
        serving.batcher:raise              # InjectedFault, every group
        checkpoint.io:raise:OSError:2      # OSError on the first 2 writes
        checkpoint.io:corrupt:1            # corrupt the first commit
        trainer.step:raise:OSError:1:6     # fail exactly the 7th step

    ``arg`` is seconds for ``delay`` and an exception name for ``raise``
    (InjectedFault, MXNetError, OSError, IOError, RuntimeError,
    TimeoutError, DeviceUnavailableError, DataCorruptionError; a bare
    ``device.unavailable:raise`` defaults to DeviceUnavailableError);
    for ``corrupt`` the first optional slot holds ``times`` directly.
    ``after`` skips that many matching occurrences first (the
    ``FaultRule`` window, so an env-driven drill can hit exactly the
    Nth step/dispatch).  Malformed specs — unknown tokens and TRAILING
    EXTRAS included — raise loudly: a silently-ignored field would make
    a chaos drill pass vacuously."""
    out = FaultPlan()
    for token in spec.replace(";", ",").split(","):
        token = token.strip()
        if not token:
            continue
        parts = token.split(":")
        if len(parts) < 2:
            raise MXNetError(f"{ENV_VAR}: rule {token!r} needs at least "
                             f"site:mode")
        site, mode, rest = parts[0], parts[1], parts[2:]
        try:
            if mode == "delay":
                if not rest:
                    raise ValueError("delay needs seconds")
                kw = {"delay_s": float(rest[0])}
            elif mode == "raise":
                kw = {}
                if rest:
                    if rest[0] not in _EXC_TYPES:
                        raise ValueError(
                            f"unknown exception {rest[0]!r} (have "
                            f"{sorted(_EXC_TYPES)})")
                    kw["exc"] = _EXC_TYPES[rest[0]]
            elif mode == "corrupt":
                # corrupt has no arg slot: times/after shift left one
                kw = {}
                rest = [None] + rest
            else:
                raise ValueError(f"unknown mode {mode!r}")
            if len(rest) > 1:
                kw["times"] = int(rest[1])
            if len(rest) > 2:
                kw["after"] = int(rest[2])
            if len(rest) > 3:
                raise ValueError(
                    f"trailing fields {rest[3:]} (syntax is "
                    "site:mode[:arg][:times[:after]])")
        except ValueError as e:
            raise MXNetError(f"{ENV_VAR}: bad rule {token!r}: {e}") from None
        out.add(site, mode, **kw)
    return out


def install_from_env() -> Optional[FaultPlan]:
    """Parse + install ``MXNET_FAULT_PLAN`` (clears when unset/empty).
    Called once at import; call again after changing the env."""
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return None
    plan_ = parse_plan(spec)
    install(plan_)
    log.warning("faultinject: %s active with %d rule(s): %s", ENV_VAR,
                len(plan_.rules()), spec)
    return plan_


install_from_env()
