"""Detection data pipeline: det-aware augmenters + ImageDetIter.

Reference parity: `python/mxnet/image/detection.py` (DetAugmenter family,
CreateDetAugmenter, ImageDetIter) and the native `ImageDetRecordIter`
(`src/io/iter_image_det_recordio.cc:582`, det augmentation
`src/io/image_det_aug_default.cc`).

Label wire format (reference `_parse_label`, detection.py:710-733):
    raw = [header_width, obj_width, ...header..., (id, xmin, ymin, xmax,
    ymax, ...) * nobj]  with normalized [0,1] corner boxes.
Batches pad to the dataset's max object count with -1 rows — exactly what
`MultiBoxTarget` consumes.  Augmentation runs host-side (numpy) in the
prefetch thread; the TPU step stays a fixed-shape compiled program.
"""
from __future__ import annotations

import numpy as _np

from . import io as _io
from . import recordio
from .base import MXNetError
from .ndarray import NDArray
from . import ndarray as nd
from . import image as _img


class DetAugmenter:
    """Detection augmenter base (parity: detection.py:37): __call__(src,
    label) -> (src, label) with label rows (id, x1, y1, x2, y2, ...)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    dumps = _img.Augmenter.dumps  # shared spec serialization

    def __call__(self, src, label):
        return src, label


class DetBorrowAug(DetAugmenter):
    """Borrow a plain image augmenter that does not move geometry
    (color/cast/normalize) — parity: detection.py:63."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        if not isinstance(src, NDArray):
            src = nd.array(_np.ascontiguousarray(src))
        src = self.augmenter(src)[0]
        return src, label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter to apply (parity: detection.py:88)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _np.random.random() < self.skip_prob or not self.aug_list:
            return src, label
        t = self.aug_list[_np.random.randint(len(self.aug_list))]
        return t(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and boxes left-right with probability p (parity:
    detection.py:124)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _np.random.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            src = arr[:, ::-1, :].copy()
            label = label.copy()
            tmp = 1.0 - label[:, 1]
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


def _box_areas(boxes):
    return _np.maximum(0, boxes[:, 2] - boxes[:, 0]) * \
        _np.maximum(0, boxes[:, 3] - boxes[:, 1])


def _crop_update_label(label, x1, y1, x2, y2, min_eject_coverage):
    """Re-express boxes in crop coordinates; eject mostly-cropped-away
    objects (parity: detection.py _update_labels)."""
    w, h = x2 - x1, y2 - y1
    boxes = label[:, 1:5]
    inter_x1 = _np.maximum(boxes[:, 0], x1)
    inter_y1 = _np.maximum(boxes[:, 1], y1)
    inter_x2 = _np.minimum(boxes[:, 2], x2)
    inter_y2 = _np.minimum(boxes[:, 3], y2)
    iw = _np.maximum(0, inter_x2 - inter_x1)
    ih = _np.maximum(0, inter_y2 - inter_y1)
    coverage = iw * ih / _np.maximum(_box_areas(boxes), 1e-12)
    keep = coverage > min_eject_coverage
    if not keep.any():
        return None
    out = label[keep].copy()
    out[:, 1] = _np.clip((inter_x1[keep] - x1) / w, 0, 1)
    out[:, 2] = _np.clip((inter_y1[keep] - y1) / h, 0, 1)
    out[:, 3] = _np.clip((inter_x2[keep] - x1) / w, 0, 1)
    out[:, 4] = _np.clip((inter_y2[keep] - y1) / h, 0, 1)
    return out


class DetRandomCropAug(DetAugmenter):
    """IOU-constrained random crop (parity: detection.py:150 — sample a
    crop from aspect_ratio/area ranges until min_object_covered holds)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = src.asnumpy() if isinstance(src, NDArray) else src
        H, W = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            cw = min(1.0, _np.sqrt(area * ratio))
            ch = min(1.0, _np.sqrt(area / ratio))
            x1 = _np.random.uniform(0, 1 - cw)
            y1 = _np.random.uniform(0, 1 - ch)
            x2, y2 = x1 + cw, y1 + ch
            boxes = label[:, 1:5]
            inter = _np.stack([_np.maximum(boxes[:, 0], x1),
                               _np.maximum(boxes[:, 1], y1),
                               _np.minimum(boxes[:, 2], x2),
                               _np.minimum(boxes[:, 3], y2)], axis=1)
            cover = _box_areas(inter) / _np.maximum(_box_areas(boxes), 1e-12)
            if cover.max(initial=0.0) < self.min_object_covered:
                continue
            new_label = _crop_update_label(label, x1, y1, x2, y2,
                                           self.min_eject_coverage)
            if new_label is None:
                continue
            px1, py1 = int(x1 * W), int(y1 * H)
            px2, py2 = max(px1 + 1, int(x2 * W)), max(py1 + 1, int(y2 * H))
            return arr[py1:py2, px1:px2, :], new_label
        return arr, label


class DetRandomPadAug(DetAugmenter):
    """Random expand/pad: place the image on a larger canvas, shrinking
    boxes accordingly (parity: detection.py:323)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = src.asnumpy() if isinstance(src, NDArray) else src
        H, W = arr.shape[:2]
        area = _np.random.uniform(*self.area_range)
        if area <= 1.0:
            return arr, label
        ratio = _np.random.uniform(*self.aspect_ratio_range)
        nw = int(W * min(4.0, _np.sqrt(area * ratio)))
        nh = int(H * min(4.0, _np.sqrt(area / ratio)))
        nw, nh = max(nw, W), max(nh, H)
        ox = _np.random.randint(0, nw - W + 1)
        oy = _np.random.randint(0, nh - H + 1)
        canvas = _np.empty((nh, nw, arr.shape[2]), arr.dtype)
        canvas[...] = _np.asarray(self.pad_val, arr.dtype)[:arr.shape[2]]
        canvas[oy:oy + H, ox:ox + W, :] = arr
        out = label.copy()
        out[:, 1] = (out[:, 1] * W + ox) / nw
        out[:, 2] = (out[:, 2] * H + oy) / nh
        out[:, 3] = (out[:, 3] * W + ox) / nw
        out[:, 4] = (out[:, 4] * H + oy) / nh
        return canvas, out


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """Compose several IOU-constrained random-crop augmenters, one per
    parameter combination, behind a random selector (parity:
    detection.py CreateMultiRandCropAugmenter — scalar parameters are
    broadcast to the longest list length)."""
    def listify(v):
        return v if isinstance(v, list) else [v]

    moc = listify(min_object_covered)
    arr_ = listify(aspect_ratio_range)
    area = listify(area_range)
    mec = listify(min_eject_coverage)
    ma = listify(max_attempts)
    n = max(len(x) for x in (moc, arr_, area, mec, ma))
    for name, lst in (("min_object_covered", moc),
                      ("aspect_ratio_range", arr_),
                      ("area_range", area),
                      ("min_eject_coverage", mec),
                      ("max_attempts", ma)):
        if len(lst) not in (1, n):
            raise ValueError(f"{name}: length {len(lst)} != {n}")
    crops = [DetRandomCropAug(
        min_object_covered=moc[i % len(moc)],
        aspect_ratio_range=arr_[i % len(arr_)],
        area_range=area[i % len(area)],
        min_eject_coverage=mec[i % len(mec)],
        max_attempts=ma[i % len(ma)]) for i in range(n)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Detection augmenter list (parity: detection.py:482
    CreateDetAugmenter — same knobs, same ordering: resize → pad → crop →
    mirror → force-resize → color → normalize)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_pad > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range,
                             (1.0, max(1.0, area_range[1])), max_attempts,
                             pad_val)],
            1 - rand_pad))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (min(area_range[0], 1.0), 1.0),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        # bool → the reference's 0.5 coin; a float is used as-is so
        # rand_mirror_prob passes through exactly
        auglist.append(DetHorizontalFlipAug(
            0.5 if rand_mirror is True else float(rand_mirror)))
    # force resize to the network input
    auglist.append(DetBorrowAug(
        _img.ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            _img.ColorJitterAug(brightness, contrast, saturation)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(_img.RandomGrayAug(rand_gray)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        # reference gate (detection.py:618): normalize when EITHER is given
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(_img.ImageIter):
    """Detection iterator over .rec/.lst/list sources (parity:
    detection.py:624 ImageDetIter): parses header/object-width labels,
    applies det augmenters, yields (B,C,H,W) data + (B, max_objs, obj_w)
    labels padded with -1."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 label_pad_width=0, label_pad_value=-1.0, **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        if aug_list is None:
            self.auglist = CreateDetAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.label_pad_value = float(label_pad_value)
        label_shape = self._estimate_label_shape()
        if label_pad_width > 0:
            if label_pad_width < label_shape[0]:
                raise MXNetError(
                    f"label_pad_width {label_pad_width} < dataset max "
                    f"object count {label_shape[0]}")
            label_shape = (label_pad_width, label_shape[1])
        self.label_shape = label_shape
        self.provide_label = [_io.DataDesc(
            label_name, (batch_size,) + label_shape)]

    @staticmethod
    def _parse_label(label):
        """Parity: detection.py:710 — raw [header_w, obj_w, ...] vector →
        (nobj, obj_w) array, invalid boxes dropped."""
        if isinstance(label, NDArray):
            label = label.asnumpy()
        raw = _np.asarray(label, _np.float32).ravel()
        if raw.size < 7:
            raise MXNetError(f"Label shape is invalid: {raw.shape}")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if (raw.size - header_width) % obj_width != 0:
            raise MXNetError(
                f"Label shape {raw.shape} inconsistent with annotation "
                f"width {obj_width}")
        out = raw[header_width:].reshape((-1, obj_width))
        valid = _np.where((out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2]))[0]
        if valid.size < 1:
            raise MXNetError("Encounter sample with no valid label.")
        return out[valid, :]

    def _estimate_label_shape(self):
        max_count, width = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self.next_sample()
                parsed = self._parse_label(label)
                max_count = max(max_count, parsed.shape[0])
                width = parsed.shape[1]
        except StopIteration:
            pass
        self.reset()
        return (max_count, width)

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.provide_data = [_io.DataDesc(
                self.provide_data[0].name, (self.batch_size,) + tuple(data_shape))]
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self.label_shape = tuple(label_shape)
            self.provide_label = [_io.DataDesc(
                self.provide_label[0].name,
                (self.batch_size,) + tuple(label_shape))]

    def _decode_augment_det(self, sample):
        raw_label, s = sample
        data = _img.imdecode(s)
        arr = data.asnumpy() if isinstance(data, NDArray) else data
        label = self._parse_label(raw_label)
        for aug in self.auglist:
            arr, label = aug(arr, label)
            if isinstance(arr, NDArray):
                arr = arr.asnumpy()
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr, label

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = _np.zeros((batch_size, h, w, c), _np.float32)
        batch_label = _np.full((batch_size,) + self.label_shape,
                               self.label_pad_value, _np.float32)
        samples = []
        while len(samples) < batch_size:
            try:
                samples.append(self.next_sample())
            except StopIteration:
                if not samples:
                    raise
                break
        results = self._map_pool(self._decode_augment_det, samples)
        i = 0
        for arr, label in results:
            batch_data[i] = arr[:h, :w, :c]
            n = min(label.shape[0], self.label_shape[0])
            batch_label[i, :n, :label.shape[1]] = label[:n]
            i += 1
        data_nchw = _np.transpose(batch_data, (0, 3, 1, 2))
        return _io.DataBatch([nd.array(data_nchw)], [nd.array(batch_label)],
                             batch_size - i,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)


def ImageDetRecordIter(path_imgrec, data_shape, batch_size, shuffle=False,
                       mean_r=0., mean_g=0., mean_b=0., std_r=1., std_g=1.,
                       std_b=1., rand_crop_prob=0., rand_pad_prob=0.,
                       rand_mirror_prob=0., label_pad_width=0,
                       label_pad_value=-1.0, preprocess_threads=4,
                       prefetch_buffer=4, **kwargs):
    """RecordIO-backed detection iterator (parity:
    src/io/iter_image_det_recordio.cc ImageDetRecordIter registration):
    det-aware augmentation in the prefetch thread, double-buffered."""
    mean = _np.array([mean_r, mean_g, mean_b]) \
        if any((mean_r, mean_g, mean_b)) else None
    std = _np.array([std_r, std_g, std_b]) \
        if any(s != 1 for s in (std_r, std_g, std_b)) else None
    it = ImageDetIter(batch_size=batch_size, data_shape=tuple(data_shape),
                      path_imgrec=path_imgrec, shuffle=shuffle,
                      rand_crop=rand_crop_prob, rand_pad=rand_pad_prob,
                      rand_mirror=rand_mirror_prob, mean=mean, std=std,
                      label_pad_width=label_pad_width,
                      label_pad_value=label_pad_value, **kwargs)
    return _io.PrefetchingIter(it, depth=int(prefetch_buffer))
