"""Imperative autograd: record/pause scopes, tape, backward.

Reference parity: `python/mxnet/autograd.py` + `src/imperative/imperative.cc`
(thread-local is_train/is_recording flags include/mxnet/imperative.h:153-172;
RecordOp tape :182; Backward :357).  TPU-native: each recorded op stores the
`jax.vjp` closure of its forward — backward is a reverse tape walk calling
those closures (no separate NNVM Gradient pass; XLA differentiates each op).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops import registry as _reg


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape: List = []


_state = _State()


def is_recording() -> bool:
    return _state.recording


def is_training() -> bool:
    return _state.training


def set_recording(is_record: bool) -> bool:
    old, _state.recording = _state.recording, is_record
    return old


def set_training(train_mode: bool) -> bool:
    old, _state.training = _state.training, train_mode
    return old


class _RecordingStateScope:
    def __init__(self, is_record: Optional[bool], train_mode: Optional[bool]):
        self._enter = (is_record, train_mode)
        self._prev = None

    def __enter__(self):
        rec, train = self._enter
        self._prev = (_state.recording, _state.training)
        if rec is not None:
            _state.recording = rec
        if train is not None:
            _state.training = train
        return self

    def __exit__(self, *exc):
        _state.recording, _state.training = self._prev


def record(train_mode: bool = True):
    """Scope in which executed ops are recorded (parity: autograd.py:122)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------
class _TapeEntry:
    # out_refs keeps the output NDArrays alive for the tape's lifetime:
    # keys are (id, version) and CPython recycles ids of collected
    # objects, so dropping the refs would let unrelated later arrays
    # alias a dead output's key (wrong-gradient corruption)
    __slots__ = ("in_keys", "in_refs", "out_keys", "out_refs", "vjp_fn",
                 "cot_zeros", "in_idx")

    def __init__(self, in_keys, in_refs, out_keys, out_refs, vjp_fn,
                 cot_zeros, in_idx=None):
        self.in_keys = in_keys
        self.in_refs = in_refs
        self.out_keys = out_keys
        self.out_refs = out_refs
        self.vjp_fn = vjp_fn       # cotangents tuple -> input grads tuple
        # (shape, dtype) spec per forward output; the zero cotangent is
        # materialized lazily in backward() and only for slots that did
        # not receive a gradient — recording must not allocate (a
        # row-sparse dot output would otherwise pin an O(vocab) dense
        # zeros buffer per recorded call)
        self.cot_zeros = cot_zeros
        # vjp-grad slot per tape input (optional tensor inputs may be None
        # in the op call — their slots exist in the vjp but not on the tape)
        self.in_idx = in_idx if in_idx is not None else list(range(len(in_keys)))


def _key(arr) -> Tuple[int, int]:
    return (id(arr), arr._version)


def _record(op, inputs, outputs, vjp_fn, raw_outs) -> None:
    """Called by ndarray.register.invoke when recording (RecordOp parity).

    `outputs` are the visible result NDArrays (their keys index the grad map);
    `raw_outs` is the full forward output tuple (visible + aux) whose
    shapes/dtypes define the cotangent structure for vjp_fn.
    """
    indexed = [(i, a) for i, a in enumerate(inputs) if hasattr(a, "_version")]
    _state.tape.append(_TapeEntry(
        [_key(a) for _, a in indexed],
        [a for _, a in indexed],
        [_key(o) for o in outputs],
        list(outputs),
        vjp_fn,
        tuple((tuple(o.shape), o.dtype) for o in raw_outs),
        in_idx=[i for i, _ in indexed]))


def _mark_variable(arr) -> None:
    pass


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Parity: autograd.mark_variables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def _acc(a, b):
    """Gradient accumulation that understands row-sparse cotangent
    markers (_RspCot): rsp+rsp stays rows-only; mixing with dense
    densifies (correct fallback, e.g. tied embeddings)."""
    from .ndarray.sparse import _RspCot
    if isinstance(a, _RspCot) or isinstance(b, _RspCot):
        return a + b if isinstance(a, _RspCot) else b + a
    return a + b


def _ones_cot(shape: Tuple[int, ...], dtype):
    """Default head cotangent — allocated FRESH each call, never cached:
    when a head is itself a leaf with attach_grad, this exact array is
    deposited as the user-visible ``.grad`` buffer, and several
    consumers donate gradient buffers into jitted programs (per-key
    ``Trainer.update``, module fit, serving).  A process-lifetime cache
    would hand out an array XLA may delete, poisoning every later
    default-seed backward of that (shape, dtype) with 'Array has been
    deleted'.  The fill is one cheap XLA op; the whole-step program
    never needs it at all — gluon/wholestep.py differentiates a summed
    loss instead."""
    return jnp.ones(shape, dtype)


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True) -> None:
    """Reverse walk of the tape from `heads` (parity: Imperative::Backward)."""
    from .ndarray.sparse import _RspCot, RowSparseNDArray
    tape = _state.tape
    grad_map: Dict[Tuple[int, int], jax.Array] = {}
    for i, h in enumerate(heads):
        hg = None if head_grads is None else head_grads[i]
        g = _ones_cot(tuple(h.shape), h.dtype) if hg is None else (
            hg._data if hasattr(hg, "_data") else jnp.asarray(hg))
        k = _key(h)
        grad_map[k] = _acc(grad_map[k], g) if k in grad_map else g

    for entry in reversed(tape):
        if not any(k in grad_map for k in entry.out_keys):
            continue
        cots = [None] * len(entry.cot_zeros)
        for j, k in enumerate(entry.out_keys):
            if k in grad_map:
                g = grad_map[k]
                if isinstance(g, _RspCot):
                    g = g.to_dense()  # upstream op needs a dense cotangent
                cots[j] = g.astype(entry.cot_zeros[j][1])
        cots = [jnp.zeros(*entry.cot_zeros[j]) if c is None else c
                for j, c in enumerate(cots)]
        in_grads = entry.vjp_fn(tuple(cots))
        for idx, k in enumerate(entry.in_keys):
            g = in_grads[entry.in_idx[idx]]
            if not isinstance(g, _RspCot):
                g = _reg.zero_like_grad(g, entry.in_refs[idx]._data)
            grad_map[k] = _acc(grad_map[k], g) if k in grad_map else g

    # write accumulated grads into attached .grad buffers
    seen = set()

    def _deposit(ref, k):
        if id(ref) in seen or ref._grad is None or ref._grad_req == "null":
            return
        if k in grad_map:
            seen.add(id(ref))
            ref._fresh_grad = True
            g = grad_map[k]
            if isinstance(ref._grad, RowSparseNDArray):
                if not isinstance(g, _RspCot):
                    # dense grad into an rsp buffer: keep only nonzero
                    # rows (correct, though the dense detour already paid)
                    from .ndarray.sparse import row_sparse_array
                    rs = row_sparse_array(g)
                    ids, vals = rs._indices, rs._values
                else:
                    ids, vals = g.ids, g.vals
                vals = vals.astype(ref._grad.dtype)
                if ref._grad_req == "add":
                    ref._grad._add_rows(ids, vals)
                else:
                    ref._grad._assign_rows(ids, vals)
                return
            if isinstance(g, _RspCot):
                g = g.to_dense()
            g = g.astype(ref._grad.dtype)
            if ref._grad_req == "add":
                ref._grad._set_data(ref._grad._data + g)
            else:
                ref._grad._set_data(g)

    for entry in tape:
        for ref, k in zip(entry.in_refs, entry.in_keys):
            _deposit(ref, k)
    for h in heads:
        _deposit(h, _key(h))

    if not retain_graph:
        _state.tape = []


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Grads of heads wrt variables (convenience; later-mxnet API)."""
    if create_graph:
        raise MXNetError("create_graph=True not supported yet")
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    for v in variables:
        if v._grad is None:
            v.attach_grad()
    backward(list(heads), head_grads, retain_graph=bool(retain_graph),
             train_mode=train_mode)
    return [v._grad for v in variables]


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported in mxnet_tpu; "
                     "use gluon HybridBlock tracing instead")


# ---------------------------------------------------------------------------
# Custom differentiable Function (parity: autograd.Function, autograd.py:495,
# backed by c_api_function.cc in the reference)
# ---------------------------------------------------------------------------
class Function:
    """User-defined op with explicit forward/backward over NDArrays."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self
            ctx = inputs[0]._ctx if inputs else None

            def vjp_fn(cots):
                with pause():
                    gin = func.backward(*[NDArray(c, ctx) for c in cots])
                gin = [gin] if not isinstance(gin, (list, tuple)) else list(gin)
                return tuple(g._data for g in gin)

            _state.tape.append(_TapeEntry(
                [_key(a) for a in inputs], list(inputs),
                [_key(o) for o in outs], list(outs), vjp_fn,
                tuple((tuple(o.shape), o.dtype) for o in outs)))
        return outputs
