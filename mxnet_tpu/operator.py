"""mx.operator — user-defined operators in python.

Reference parity: `python/mxnet/operator.py:418-598` — the CustomOp /
CustomOpProp / register contract every `example/numpy-ops/` demo depends
on, backed by `src/operator/custom/custom.cc:37-79` (frontend callback op).

TPU-native realization: registered props feed the `Custom` operator
(`mxnet_tpu/ops/custom.py`), whose forward/backward run the user's numpy
code as `jax.pure_callback` host calls inside otherwise fully-jitted
graphs; gradients wire through `jax.custom_vjp`.  Works in `mx.nd.Custom`,
`mx.sym.Custom(... op_type=name)`, Module training, and autograd.
"""
from __future__ import annotations

from .base import MXNetError
from .ops.custom import CUSTOM_PROP_REGISTRY


class CustomOp:
    """Base class for operators implemented in python (parity:
    operator.py:418)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute out_data from in_data; use self.assign(dst, req, src)."""

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute in_grad; use self.assign(dst, req, src)."""

    def assign(self, dst, req, src):
        """Assign src into dst honoring the write request."""
        if req == "null":
            return
        elif req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] += src


class CustomOpProp:
    """Base class for custom-operator property classes (parity:
    operator.py:464): declares arguments/outputs and shape/type inference,
    and creates the CustomOp that does the math."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, (in_shape[0],) * len(self.list_outputs()), ()

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp subclass under `op_type=reg_name` (parity:
    operator.py register)."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(
                "can only register subclasses of CustomOpProp")
        CUSTOM_PROP_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered_operators():
    return list(CUSTOM_PROP_REGISTRY)


# -- legacy v0.x interfaces (parity: operator.py NativeOp/NDArrayOp) ---------
class PythonOp:
    """Deprecated v0.x base — superseded by CustomOp/CustomOpProp."""

    def __init__(self, *a, **kw):
        raise MXNetError("PythonOp is deprecated; use "
                         "mx.operator.CustomOp + CustomOpProp + register")


class NativeOp(PythonOp):
    pass


class NDArrayOp(PythonOp):
    pass


class NumpyOp(PythonOp):
    """Deprecated v0.x numpy custom-op base (parity: operator.py
    NumpyOp) — superseded by CustomOp/CustomOpProp."""
