"""Sparse-storage operators with symbol-space presence.

Reference parity: `src/operator/tensor/cast_storage-inl.h`,
`sparse_retain-inl.h`, `square_sum-inl.h`, and the sparse forms of `dot`
(`dot-inl.h`).  TPU-native stance (SURVEY.md §7): XLA has no first-class
sparsity, so compute lowers to dense masks/gathers with the reference's
*semantics* (which rows exist, what gradients flow) preserved; the
NDArray layer re-wraps results in the right storage class.  This is the
documented dense-compute fallback — correct everywhere, fast where the
MXU wants it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import Arg
from .registry import register


@register("cast_storage", input_names=("data",),
          args=[Arg("stype", str, required=True)])
def _cast_storage(p, x):
    """Parity: cast_storage-inl.h — storage conversion.  Value-level
    identity (storage class handled by the NDArray wrapper); present in
    symbol graphs so reference models serialize/execute unchanged."""
    return x


@register("sparse_retain", input_names=("data", "indices"))
def _sparse_retain(p, x, idx):
    """Parity: sparse_retain-inl.h — keep only the listed rows.

    Dense lowering: scatter a row mask and zero everything else; the
    gradient flows only through retained rows (matching the reference's
    backward which is itself a sparse_retain)."""
    mask = jnp.zeros((x.shape[0],), jnp.bool_).at[
        idx.astype(jnp.int32)].set(True)
    bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
    return jnp.where(mask.reshape(bshape), x, jnp.zeros((), x.dtype))


@register("_square_sum", input_names=("data",), aliases=("square_sum",),
          args=[Arg("axis", "shape", None), Arg("keepdims", bool, False),
                Arg("exclude", bool, False)])
def _square_sum(p, x):
    """Parity: square_sum-inl.h — fused sum(x**2) (rsp-optimized in the
    reference; one fused XLA reduction here)."""
    axis = p["axis"]
    if axis is not None and len(axis) == 0:
        axis = None
    if axis is not None and p["exclude"]:
        axis = tuple(i for i in range(x.ndim) if i not in
                     tuple(a % x.ndim for a in axis))
    return jnp.sum(jnp.square(x), axis=axis, keepdims=p["keepdims"])
