"""Operator registry: single-definition ops that serve both `nd.*` and `sym.*`.

Reference parity: replaces the NNVM op registry + FCompute dispatch
(`include/mxnet/op_attr_types.h`, `src/operator/mxnet_op.h:355-372`) and the
per-op CUDA kernels.  Each op here is ONE pure-JAX forward function; gradients
come from `jax.vjp` (replacing hand-written Backward kernels and the NNVM
`Gradient` pass), and eager execution goes through a cached `jax.jit` per
(op, params) — XLA is the kernel author, fuser, and scheduler.

The registry drives mechanical codegen of `mx.nd.*` and `mx.sym.*` functions
(parity: python/mxnet/ndarray/register.py:31-47 autogen from
MXSymbolListAtomicSymbolCreators).
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as _np

from ..base import Arg, MXNetError, ParamSchema

# name -> Operator
OP_REGISTRY: Dict[str, "Operator"] = {}
# alias -> canonical name
OP_ALIASES: Dict[str, str] = {}


@dataclass
class Operator:
    """One operator definition.

    fn(params: dict, *inputs) -> jax array | tuple of jax arrays
      - params: normalized kwargs (plus '__is_train__' if takes_is_train)
      - inputs: jax arrays (plus a PRNG key appended last if needs_rng)
    """

    name: str
    fn: Callable
    input_names: List[str]
    schema: ParamSchema
    num_outputs: int = 1
    # indices of input_names that are auxiliary states (BatchNorm moving stats):
    # fn must return extra trailing outputs, one per aux input, holding the
    # updated aux value; eager invoke writes them back into the aux NDArrays.
    aux_inputs: List[int] = field(default_factory=list)
    variadic: bool = False          # takes *args (Concat, add_n, stack)
    needs_rng: bool = False         # appends a PRNG key input
    takes_is_train: bool = False    # receives '__is_train__' in params
    mutates_input: Optional[int] = None  # optimizer ops update this input in place
    differentiable: bool = True
    # input positions that stay float32 under reduced-precision training
    # (BN scale/stats — cuDNN contract the reference mirrors; class-id /
    # index inputs where bf16's 8-bit mantissa corrupts ids > 256).
    # infer_type consults this instead of a name-keyed side table.
    f32_inputs: Tuple[int, ...] = ()
    # optional custom vjp: bwd(params, primals, out_grads) -> input grads
    docstring: str = ""
    # `impl` values for which this op runs sequence-parallel shard_map
    # over the ambient sp mesh: eager dispatch and make_vjp must place
    # arrays on the mesh instead of the single-device jit wrapper.
    # Declared by the op itself (flash_attention.py), so a future op
    # whose unrelated 'impl' param happens to say "ring" is unaffected.
    sp_impls: Tuple[str, ...] = ()

    def normalize(self, kwargs) -> Tuple[Tuple[str, Any], ...]:
        return self.schema.normalize(kwargs)

    @property
    def total_outputs(self) -> int:
        return self.num_outputs + len(self.aux_inputs)


def register(name, input_names=("data",), args: Sequence[Arg] = (),
             num_outputs: int = 1, aliases: Sequence[str] = (), **flags):
    """Decorator registering a pure-jax forward as a framework operator."""

    def _reg(fn):
        op = Operator(
            name=name,
            fn=fn,
            input_names=list(input_names),
            schema=ParamSchema(list(args)),
            num_outputs=num_outputs,
            docstring=fn.__doc__ or "",
            **flags,
        )
        if name in OP_REGISTRY:
            raise MXNetError(f"op '{name}' registered twice")
        OP_REGISTRY[name] = op
        for a in aliases:
            OP_ALIASES[a] = name
        _attach_frontends(name, aliases)
        return fn

    return _reg


# Frontend attach hooks: the nd/sym register modules append a
# callback(op_name) here at import time; late registrations (a user op
# registered AFTER import — the docs/faq/new_op.md workflow; parity
# with the reference, where custom creators appear in the enumerated
# op list immediately) replay through them so mx.nd.*/mx.sym.* pick
# the new op up.  Empty during the initial import pass (populate()
# builds the full table then).
FRONTEND_ATTACH_HOOKS: List = []


def _attach_frontends(name, aliases):
    for hook in FRONTEND_ATTACH_HOOKS:
        for nm in (name, *aliases):
            hook(nm)


def get_op(name: str) -> Operator:
    cname = OP_ALIASES.get(name, name)
    if cname not in OP_REGISTRY:
        raise MXNetError(f"operator '{name}' not registered")
    return OP_REGISTRY[cname]


def list_ops() -> List[str]:
    return sorted(OP_REGISTRY) + sorted(OP_ALIASES)


# ---------------------------------------------------------------------------
# Eager execution: cached jit per (op, params)
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _jitted(op_name: str, params: Tuple[Tuple[str, Any], ...],
            layout: str = "NCHW"):
    # `layout` is only a cache key: spatial ops trace
    # mxnet_tpu.layout.conv_layout() at trace time, so a flag flip must
    # miss the cache and re-trace
    op = OP_REGISTRY[op_name]
    pd = dict(params)

    def run(*inputs):
        out = op.fn(pd, *inputs)
        return out if isinstance(out, tuple) else (out,)

    return jax.jit(run)


def apply_op(op: Operator, params: Tuple[Tuple[str, Any], ...], inputs) -> Tuple:
    """Run the op on raw jax arrays; returns a tuple of all outputs (incl aux).

    Under an outer jax trace (symbolic executor inside jit) the op fn is
    inlined directly: a nested jit would be redundant for fusion and jax
    0.9 cannot linearize some primitives through a nested pjit (e.g.
    reduce_window_sum — avg-pool backward dies with 'Linearization
    failed to produce known values').

    Works both eagerly and under an outer jax trace (the symbolic executor
    calls this inside jit — XLA then fuses across ops, which is the TPU
    replacement for reference op-bulking, src/executor/graph_executor.cc:1350).
    """
    if any(isinstance(a, jax.core.Tracer) for a in inputs if a is not None):
        pd = dict(params)
        out = op.fn(pd, *inputs)
        return out if isinstance(out, tuple) else (out,)
    pd = dict(params)
    if op.sp_impls and pd.get("impl") in op.sp_impls:
        # sequence-parallel impls shard over the ambient sp mesh: run
        # the fn EAGERLY (shard_map places its own devices) — the
        # single-device _jitted wrapper would conflict with the mesh
        out = op.fn(pd, *inputs)
        return out if isinstance(out, tuple) else (out,)
    from .. import layout as _layout
    return _jitted(op.name, params, _layout.conv_layout())(*inputs)


@functools.lru_cache(maxsize=None)
def _sp_fwd_bwd(op_name: str, params: Tuple[Tuple[str, Any], ...],
                mesh, axis_name: str):
    """Cached jitted forward + vjp-backward for a sequence-parallel op
    under eager autograd (same idiom as _jitted).  The ambient scope's
    (mesh, axis) pair is captured at trace time inside op.fn, so BOTH
    are cache keys — the same mesh under a different sp axis must
    trace fresh.  jax.jit caches per input shape under each entry."""
    op = OP_REGISTRY[op_name]
    pd = dict(params)

    def run(*ins):
        out = op.fn(pd, *ins)
        return out if isinstance(out, tuple) else (out,)

    def bwd(ins, cts):
        _, vjp_fn = jax.vjp(run, *ins)
        return vjp_fn(tuple(cts))

    fwd_j, bwd_j = jax.jit(run), jax.jit(bwd)

    # jax.jit traces LAZILY (first call, and again per new input
    # shape) and op.fn reads the AMBIENT scope at trace time — so a
    # backward() issued after the user's `with sp_scope(...)` exited
    # (or under a different scope) would trace against the wrong/no
    # mesh and poison this cache entry.  Re-enter the KEYED scope
    # around every call: traces always see exactly the (mesh, axis)
    # this entry is keyed on; the push/pop is a list append when no
    # trace happens.
    from ..parallel.sequence_parallel import sp_scope

    def fwd_scoped(*ins):
        with sp_scope(mesh, axis_name):
            return fwd_j(*ins)

    def bwd_scoped(ins, cts):
        with sp_scope(mesh, axis_name):
            return bwd_j(ins, cts)

    return fwd_scoped, bwd_scoped


def make_vjp(op: Operator, params: Tuple[Tuple[str, Any], ...], inputs):
    """Forward + vjp closure for autograd (replaces hand-written Backwards)."""
    pd = dict(params)

    def run(*ins):
        out = op.fn(pd, *ins)
        return out if isinstance(out, tuple) else (out,)

    if op.sp_impls and pd.get("impl") in op.sp_impls:
        # Sequence-parallel op under eager autograd: jax.vjp traces
        # op.fn, so the fn's own concrete-input resharding never runs —
        # place primals on the ambient sp mesh (replicated: valid for
        # any op semantics; the inner shard_map re-shards to its specs)
        # BEFORE tracing, and round-trip outputs / cotangents / grads
        # so single-device eager neighbors compose.  The fwd and bwd
        # are CACHED jits keyed on (op, params, mesh): a fresh
        # jax.vjp per call re-traced the shard_map every training step
        # (~13s/step on the CPU mesh for the sp LM example); the bwd
        # recomputes the forward inside one compiled program — the
        # standard remat trade for cacheability.
        from ..parallel import sequence_parallel as _sp
        from jax.sharding import NamedSharding, PartitionSpec as _P
        mesh, _axis = _sp.current_sp_scope()
        repl = NamedSharding(mesh, _P())
        devs = [list(a.devices()) for a in inputs
                if hasattr(a, "devices")]
        orig = devs[0][0] if devs and len(devs[0]) == 1 else None

        def to_mesh(a):
            # transient mesh staging of caller-owned (already
            # attributed) arrays — freed when the sp op returns
            return jax.device_put(a, repl) if hasattr(a, "devices") else a  # graft-lint: disable=memory-hygiene

        fwd, bwd = _sp_fwd_bwd(op.name, params, mesh, _axis)
        mesh_ins = tuple(to_mesh(a) for a in inputs)
        outs = fwd(*mesh_ins)
        if orig is not None:
            outs = tuple(jax.device_put(o, orig) for o in outs)  # graft-lint: disable=memory-hygiene

            def vjp_back(cts):
                grads = bwd(mesh_ins, tuple(to_mesh(c) for c in cts))
                return tuple(jax.device_put(g, orig) for g in grads)  # graft-lint: disable=memory-hygiene

            return outs, vjp_back
        return outs, lambda cts: bwd(mesh_ins, tuple(cts))

    return jax.vjp(run, *inputs)


def zero_like_grad(g, primal):
    """Convert jax's float0 / None gradients into dense zeros."""
    if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
        import jax.numpy as jnp
        return jnp.zeros(_np.shape(primal), _np.result_type(primal))
    return g
