"""Vision-specific legacy ops: ROIPooling, GridGenerator, BilinearSampler,
SpatialTransformer, Crop, Correlation (parity: src/operator/{roi_pooling,
grid_generator,bilinear_sampler,spatial_transformer,crop,correlation}.cc).

All are pure-jax gather/einsum formulations — XLA fuses them; gradients via
jax.vjp (the reference hand-wrote CUDA backward kernels for each).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import Arg, MXNetError
from .registry import register


@register("ROIPooling", input_names=("data", "rois"),
          args=[Arg("pooled_size", "shape", required=True),
                Arg("spatial_scale", float, required=True)])
def _roi_pooling(p, data, rois):
    """Max-pool each ROI to pooled_size (parity: roi_pooling-inl.h).

    data: (N,C,H,W); rois: (R,5) [batch_idx, x1, y1, x2, y2] in image coords.
    """
    ph, pw = p["pooled_size"]
    scale = p["spatial_scale"]
    N, C, H, W = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        roi_h = jnp.maximum(y2 - y1 + 1, 1)
        roi_w = jnp.maximum(x2 - x1 + 1, 1)
        img = data[bidx]  # (C,H,W)
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def pool_cell(iy, ix):
            hstart = y1 + (iy * roi_h) // ph
            hend = y1 + ((iy + 1) * roi_h + ph - 1) // ph
            wstart = x1 + (ix * roi_w) // pw
            wend = x1 + ((ix + 1) * roi_w + pw - 1) // pw
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            masked = jnp.where(mask[None], img, -jnp.inf)
            val = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(val), val, 0.0)

        grid = jax.vmap(lambda iy: jax.vmap(lambda ix: pool_cell(iy, ix))(
            jnp.arange(pw)))(jnp.arange(ph))  # (ph,pw,C)
        return jnp.transpose(grid, (2, 0, 1))

    return jax.vmap(one_roi)(rois.astype(data.dtype))


@register("GridGenerator", input_names=("data",),
          args=[Arg("transform_type", str, required=True),
                Arg("target_shape", "shape", ())])
def _grid_generator(p, data):
    """Parity: grid_generator.cc — affine (N,6)→grid or warp passthrough."""
    if p["transform_type"] == "affine":
        h, w = p["target_shape"]
        theta = data.reshape(-1, 2, 3)
        # dtype pinned: under x64 linspace defaults to f64 and would
        # promote the whole sampling path (found by the finite-diff
        # tier: executor output dtype flipped f32->f64)
        ys = jnp.linspace(-1, 1, h, dtype=data.dtype)
        xs = jnp.linspace(-1, 1, w, dtype=data.dtype)
        grid_x, grid_y = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(grid_x)
        base = jnp.stack([grid_x.ravel(), grid_y.ravel(), ones.ravel()])
        out = jnp.einsum("nij,jk->nik", theta, base)  # (N,2,h*w)
        return out.reshape(-1, 2, h, w)
    if p["transform_type"] == "warp":
        # data: (N,2,H,W) flow field → absolute sampling grid in [-1,1]
        N, _, H, W = data.shape
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)
        gx, gy = jnp.meshgrid(xs, ys)
        x = (data[:, 0] + gx) * 2 / jnp.maximum(W - 1, 1) - 1
        y = (data[:, 1] + gy) * 2 / jnp.maximum(H - 1, 1) - 1
        return jnp.stack([x, y], axis=1)
    raise MXNetError(f"unknown transform_type {p['transform_type']}")


def _bilinear_sample(img, grid):
    """img (C,H,W), grid (2,Ho,Wo) in [-1,1] → (C,Ho,Wo)."""
    C, H, W = img.shape
    x = (grid[0] + 1) * (W - 1) / 2
    y = (grid[1] + 1) * (H - 1) / 2
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def gather(yy, xx):
        valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
        yy = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
        xx = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
        vals = img[:, yy, xx]
        return jnp.where(valid[None], vals, 0.0)

    out = (gather(y0, x0) * (1 - wy)[None] * (1 - wx)[None] +
           gather(y0, x0 + 1) * (1 - wy)[None] * wx[None] +
           gather(y0 + 1, x0) * wy[None] * (1 - wx)[None] +
           gather(y0 + 1, x0 + 1) * wy[None] * wx[None])
    return out


@register("BilinearSampler", input_names=("data", "grid"))
def _bilinear_sampler(p, data, grid):
    """Parity: bilinear_sampler.cc — sample data at grid locations."""
    return jax.vmap(_bilinear_sample)(data, grid)


@register("SpatialTransformer", input_names=("data", "loc"),
          args=[Arg("target_shape", "shape", ()),
                Arg("transform_type", str, "affine"),
                Arg("sampler_type", str, "bilinear")])
def _spatial_transformer(p, data, loc):
    """Parity: spatial_transformer.cc — affine STN."""
    grid = _grid_generator({"transform_type": "affine",
                            "target_shape": p["target_shape"]}, loc)
    return jax.vmap(_bilinear_sample)(data, grid)


@register("Crop", input_names=("args",), variadic=True,
          args=[Arg("num_args", int, required=True), Arg("offset", "shape", (0, 0)),
                Arg("h_w", "shape", (0, 0)), Arg("center_crop", bool, False)])
def _crop_op(p, *xs):
    """Parity: src/operator/crop.cc — crop x to like-shape or h_w."""
    x = xs[0]
    if len(xs) == 2:
        th, tw = xs[1].shape[2], xs[1].shape[3]
    else:
        th, tw = p["h_w"]
    H, W = x.shape[2], x.shape[3]
    if p["center_crop"]:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = p["offset"]
    return x[:, :, y0:y0 + th, x0:x0 + tw]


@register("Correlation", input_names=("data1", "data2"),
          args=[Arg("kernel_size", int, 1), Arg("max_displacement", int, 1),
                Arg("stride1", int, 1), Arg("stride2", int, 1),
                Arg("pad_size", int, 0), Arg("is_multiply", bool, True)])
def _correlation(p, a, b):
    """Parity: correlation.cc — FlowNet-style patch correlation (kernel=1
    fast path; larger kernels via mean pooling of the product)."""
    pad = p["pad_size"]
    d = p["max_displacement"]
    s2 = p["stride2"]
    apad = jnp.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    bpad = jnp.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    N, C, H, W = a.shape
    offsets = [(dy, dx) for dy in range(-d, d + 1, s2)
               for dx in range(-d, d + 1, s2)]
    outs = []
    for dy, dx in offsets:
        shifted = jnp.roll(bpad, (-dy, -dx), axis=(2, 3))
        if p["is_multiply"]:
            prod = apad * shifted
        else:
            prod = jnp.abs(apad - shifted)
        outs.append(jnp.mean(prod, axis=1))
    out = jnp.stack(outs, axis=1)  # (N, D*D, Hp, Wp)
    return out[:, :, pad:pad + H, pad:pad + W]
