"""Shape/layout/indexing/linear-algebra operators.

Reference parity: `src/operator/tensor/matrix_op*.cc` (Reshape with MXNet's
special codes, transpose, slice family, Concat, stack, split, tile, repeat,
reverse, dot/batch_dot), `src/operator/tensor/indexing_op.cc` (take,
Embedding, one_hot, pick, gather_nd, scatter_nd), `src/operator/tensor/
control_flow_op.cc` (where), `src/operator/swapaxis.cc`, `src/operator/pad.cc`,
`src/operator/crop.cc`, `src/operator/slice_channel.cc`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import Arg, MXNetError
from .registry import register


def infer_reshape(shape, src_shape):
    """MXNet Reshape special codes (parity: matrix_op-inl.h ReshapeParam):
    0 = copy this dim; -1 = infer; -2 = copy all remaining dims;
    -3 = merge next two src dims; -4 = split one src dim by the next two
    target entries."""
    src = list(src_shape)
    out = []
    i = 0  # position in src
    j = 0  # position in shape spec
    spec = list(shape)
    while j < len(spec):
        s = spec[j]
        if s > 0:
            out.append(s)
            i += 1
        elif s == 0:
            out.append(src[i])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        elif s == -2:
            out.extend(src[i:])
            i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1])
            i += 2
        elif s == -4:
            d1, d2 = spec[j + 1], spec[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2])
            j += 2
            i += 1
        else:
            raise MXNetError(f"bad reshape code {s}")
        j += 1
    if out.count(-1) > 1:
        raise MXNetError("only one -1 allowed in reshape")
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


@register("Reshape", input_names=("data",), aliases=("reshape",),
          args=[Arg("shape", "shape", ()), Arg("reverse", bool, False)])
def _reshape(p, x):
    return jnp.reshape(x, infer_reshape(p["shape"], x.shape))


@register("Flatten", input_names=("data",), aliases=("flatten",))
def _flatten(p, x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose", input_names=("data",), args=[Arg("axes", "shape", ())])
def _transpose(p, x):
    axes = p["axes"] or None
    return jnp.transpose(x, axes)


@register("expand_dims", input_names=("data",), args=[Arg("axis", int, required=True)])
def _expand_dims(p, x):
    return jnp.expand_dims(x, p["axis"])


@register("squeeze", input_names=("data",), args=[Arg("axis", "shape", None)])
def _squeeze(p, x):
    ax = p.get("axis")
    return jnp.squeeze(x, axis=tuple(a % x.ndim for a in ax) if ax else None)


@register("SwapAxis", input_names=("data",), aliases=("swapaxes",),
          args=[Arg("dim1", int, 0), Arg("dim2", int, 0)])
def _swapaxes(p, x):
    return jnp.swapaxes(x, p["dim1"], p["dim2"])


def _canon_slice(begin, end, step, shape):
    """Normalize MXNet slice params (None/negative entries) to concrete starts/stops."""
    ndim = len(shape)
    step = step or (1,) * len(begin)
    starts, stops, strides = [], [], []
    for ax in range(ndim):
        if ax < len(begin):
            b = begin[ax]
            e = end[ax] if ax < len(end) else None
            s = step[ax] if ax < len(step) else 1
        else:
            b, e, s = None, None, 1
        s = 1 if s in (None, 0) else s
        sl = slice(b, e, s).indices(shape[ax])
        starts.append(sl[0]); stops.append(sl[1]); strides.append(sl[2])
    return starts, stops, strides


@register("slice", input_names=("data",), aliases=("crop",),
          args=[Arg("begin", "shape", required=True), Arg("end", "shape", required=True),
                Arg("step", "shape", None)])
def _slice(p, x):
    starts, stops, strides = _canon_slice(p["begin"], p["end"], p.get("step"), x.shape)
    return x[tuple(slice(b, e, s) for b, e, s in zip(starts, stops, strides))]


@register("slice_axis", input_names=("data",),
          args=[Arg("axis", int, required=True), Arg("begin", int, required=True),
                Arg("end", int, None)])
def _slice_axis(p, x):
    ax = p["axis"] % x.ndim
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(p["begin"], p["end"])
    return x[tuple(idx)]


@register("slice_like", input_names=("data", "shape_like"),
          args=[Arg("axes", "shape", ())])
def _slice_like(p, x, y):
    axes = p["axes"] or tuple(range(min(x.ndim, y.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a % x.ndim] = slice(0, y.shape[a % x.ndim])
    return x[tuple(idx)]


@register("Concat", input_names=("args",), variadic=True, aliases=("concat",),
          args=[Arg("dim", int, 1), Arg("num_args", int, 0)])
def _concat(p, *xs):
    # __io_layout__ == "NHWC" (GraphPlan whole-graph layout pass):
    # inputs are physically channels-last and dim names the LOGICAL
    # (NCHW) channel axis 1 — concat over the last axis instead, so
    # densenet/inception-style concat chains stay channels-last
    if p.get("__io_layout__") == "NHWC":
        return jnp.concatenate(xs, axis=xs[0].ndim - 1)
    return jnp.concatenate(xs, axis=p["dim"])


@register("stack", input_names=("args",), variadic=True,
          args=[Arg("axis", int, 0), Arg("num_args", int, 0)])
def _stack(p, *xs):
    return jnp.stack(xs, axis=p["axis"])


@register("SliceChannel", input_names=("data",), aliases=("split",),
          args=[Arg("num_outputs", int, required=True), Arg("axis", int, 1),
                Arg("squeeze_axis", bool, False)],
          num_outputs=-1)
def _slice_channel(p, x):
    parts = jnp.split(x, p["num_outputs"], axis=p["axis"])
    if p["squeeze_axis"]:
        parts = [jnp.squeeze(t, axis=p["axis"]) for t in parts]
    return tuple(parts)


@register("tile", input_names=("data",), args=[Arg("reps", "shape", required=True)])
def _tile(p, x):
    return jnp.tile(x, p["reps"])


@register("repeat", input_names=("data",),
          args=[Arg("repeats", int, required=True), Arg("axis", int, None)])
def _repeat(p, x):
    return jnp.repeat(x, p["repeats"], axis=p.get("axis"))


@register("reverse", input_names=("data",), aliases=("flip",),
          args=[Arg("axis", "shape", required=True)])
def _reverse(p, x):
    return jnp.flip(x, axis=p["axis"])


@register("Pad", input_names=("data",), aliases=("pad",),
          args=[Arg("mode", str, "constant"), Arg("pad_width", "shape", required=True),
                Arg("constant_value", float, 0.0)])
def _pad(p, x):
    pw = p["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[p["mode"]]
    if mode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=p["constant_value"])
    return jnp.pad(x, pairs, mode=mode)


@register("broadcast_to", input_names=("data",), args=[Arg("shape", "shape", required=True)])
def _broadcast_to(p, x):
    tgt = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(p["shape"]))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", input_names=("data",), aliases=("broadcast_axes",),
          args=[Arg("axis", "shape", ()), Arg("size", "shape", ())])
def _broadcast_axis(p, x):
    tgt = list(x.shape)
    for a, s in zip(p["axis"], p["size"]):
        tgt[a % x.ndim] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("broadcast_like", input_names=("lhs", "rhs"))
def _broadcast_like(p, x, y):
    return jnp.broadcast_to(x, y.shape)


@register("zeros_like", input_names=("data",))
def _zeros_like(p, x):
    return jnp.zeros_like(x)


@register("ones_like", input_names=("data",))
def _ones_like(p, x):
    return jnp.ones_like(x)


@register("where", input_names=("condition", "x", "y"))
def _where(p, c, x, y):
    return jnp.where(c != 0 if c.dtype != jnp.bool_ else c, x, y)


# ---------------------------------------------------------------------------
# dot / batch_dot — straight to the MXU
# ---------------------------------------------------------------------------
@register("dot", input_names=("lhs", "rhs"),
          args=[Arg("transpose_a", bool, False), Arg("transpose_b", bool, False)])
def _dot(p, a, b):
    """Parity: src/operator/tensor/dot-inl.h (dense path).

    MXNet dot on >2-D: reshapes lhs to (prod(shape[:-1]), shape[-1]) matrix
    semantics; we use tensordot over last/first axes which matches the
    reference's documented behavior for ndim>2."""
    if p["transpose_a"]:
        a = jnp.moveaxis(a, -1, 0) if a.ndim > 2 else a.T
    if p["transpose_b"]:
        b = jnp.moveaxis(b, 0, -1) if b.ndim > 2 else b.T
    if a.ndim <= 2 and b.ndim <= 2:
        return jnp.matmul(a, b)
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot", input_names=("lhs", "rhs"),
          args=[Arg("transpose_a", bool, False), Arg("transpose_b", bool, False)])
def _batch_dot(p, a, b):
    if p["transpose_a"]:
        a = jnp.swapaxes(a, -1, -2)
    if p["transpose_b"]:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


# ---------------------------------------------------------------------------
# Indexing (parity: src/operator/tensor/indexing_op.cc)
# ---------------------------------------------------------------------------
@register("take", input_names=("a", "indices"), f32_inputs=(1,),
          args=[Arg("axis", int, 0), Arg("mode", str, "clip")])
def _take(p, a, idx):
    mode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[p["mode"]]
    return jnp.take(a, idx.astype(jnp.int32), axis=p["axis"], mode=mode)


@register("batch_take", input_names=("a", "indices"), f32_inputs=(1,))
def _batch_take(p, a, idx):
    return jnp.take_along_axis(a, idx.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("Embedding", input_names=("data", "weight"), f32_inputs=(0,),
          args=[Arg("input_dim", int, required=True), Arg("output_dim", int, required=True),
                Arg("dtype", str, "float32"), Arg("sparse_grad", bool, False)])
def _embedding(p, data, weight):
    """Embedding lookup; grad wrt weight is a scatter-add via jax.vjp
    (parity: indexing_op.h EmbeddingOpForward/Backward)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0, mode="clip")


@register("one_hot", input_names=("indices",), f32_inputs=(0,),
          args=[Arg("depth", int, required=True), Arg("on_value", float, 1.0),
                Arg("off_value", float, 0.0), Arg("dtype", str, "float32")],
          differentiable=False)
def _one_hot(p, idx):
    from ..base import np_dtype
    oh = jax.nn.one_hot(idx.astype(jnp.int32), p["depth"])
    out = oh * (p["on_value"] - p["off_value"]) + p["off_value"]
    return out.astype(np_dtype(p["dtype"]))


@register("pick", input_names=("data", "index"), f32_inputs=(1,),
          args=[Arg("axis", int, -1), Arg("keepdims", bool, False),
                Arg("mode", str, "clip")])
def _pick(p, x, idx):
    ax = p["axis"] % x.ndim
    idxe = jnp.expand_dims(idx.astype(jnp.int32), ax)
    out = jnp.take_along_axis(x, jnp.clip(idxe, 0, x.shape[ax] - 1), axis=ax)
    return out if p["keepdims"] else jnp.squeeze(out, axis=ax)


@register("gather_nd", input_names=("data", "indices"), f32_inputs=(1,))
def _gather_nd(p, data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", input_names=("data", "indices"),
          args=[Arg("shape", "shape", required=True)])
def _scatter_nd(p, data, indices):
    idx = indices.astype(jnp.int32)
    out = jnp.zeros(p["shape"], data.dtype)
    return out.at[tuple(idx[i] for i in range(idx.shape[0]))].add(data)


# ---------------------------------------------------------------------------
# Linear algebra (parity: src/operator/tensor/la_op.cc — subset)
# ---------------------------------------------------------------------------
@register("linalg_gemm", input_names=("A", "B", "C"),
          args=[Arg("transpose_a", bool, False), Arg("transpose_b", bool, False),
                Arg("alpha", float, 1.0), Arg("beta", float, 1.0)])
def _linalg_gemm(p, a, b, c):
    if p["transpose_a"]:
        a = jnp.swapaxes(a, -1, -2)
    if p["transpose_b"]:
        b = jnp.swapaxes(b, -1, -2)
    return p["alpha"] * jnp.matmul(a, b) + p["beta"] * c


@register("linalg_gemm2", input_names=("A", "B"),
          args=[Arg("transpose_a", bool, False), Arg("transpose_b", bool, False),
                Arg("alpha", float, 1.0)])
def _linalg_gemm2(p, a, b):
    if p["transpose_a"]:
        a = jnp.swapaxes(a, -1, -2)
    if p["transpose_b"]:
        b = jnp.swapaxes(b, -1, -2)
    return p["alpha"] * jnp.matmul(a, b)


@register("linalg_potrf", input_names=("A",))
def _linalg_potrf(p, a):
    return jnp.linalg.cholesky(a)


@register("linalg_potri", input_names=("A",))
def _linalg_potri(p, a):
    inv = jax.scipy.linalg.cho_solve((a, True), jnp.broadcast_to(
        jnp.eye(a.shape[-1], dtype=a.dtype), a.shape))
    return inv


@register("linalg_trsm", input_names=("A", "B"),
          args=[Arg("transpose", bool, False), Arg("rightside", bool, False),
                Arg("alpha", float, 1.0), Arg("lower", bool, True)])
def _linalg_trsm(p, a, b):
    tri = jax.scipy.linalg.solve_triangular
    if p["rightside"]:
        out = tri(jnp.swapaxes(a, -1, -2), jnp.swapaxes(b, -1, -2),
                  lower=not p["lower"], trans=1 if p["transpose"] else 0)
        out = jnp.swapaxes(out, -1, -2)
    else:
        out = tri(a, b, lower=p["lower"], trans=1 if p["transpose"] else 0)
    return p["alpha"] * out


@register("linalg_trmm", input_names=("A", "B"),
          args=[Arg("transpose", bool, False), Arg("rightside", bool, False),
                Arg("alpha", float, 1.0), Arg("lower", bool, True)])
def _linalg_trmm(p, a, b):
    tril = jnp.tril(a) if p["lower"] else jnp.triu(a)
    if p["transpose"]:
        tril = jnp.swapaxes(tril, -1, -2)
    out = jnp.matmul(b, tril) if p["rightside"] else jnp.matmul(tril, b)
    return p["alpha"] * out


@register("linalg_sumlogdiag", input_names=("A",))
def _linalg_sumlogdiag(p, a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_syrk", input_names=("A",),
          args=[Arg("transpose", bool, False), Arg("alpha", float, 1.0)])
def _linalg_syrk(p, a):
    at = jnp.swapaxes(a, -1, -2)
    return p["alpha"] * (jnp.matmul(at, a) if p["transpose"] else jnp.matmul(a, at))
