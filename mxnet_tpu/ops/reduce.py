"""Reduction and ordering operators.

Reference parity: `src/operator/tensor/broadcast_reduce_op*.cc` (sum, mean,
prod, max, min, norm, argmax/argmin with axis/keepdims/exclude semantics) and
`src/operator/tensor/ordering_op.cc` (sort, argsort, topk).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import Arg
from .registry import register

_REDUCE_ARGS = [Arg("axis", "shape", None), Arg("keepdims", bool, False),
                Arg("exclude", bool, False)]


def _norm_axis(p, ndim):
    axis = p.get("axis")
    if axis is None or axis == ():
        axes = tuple(range(ndim))
    else:
        axes = tuple(a % ndim for a in axis)
    if p.get("exclude"):
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _reduce(fn):
    def run(p, x):
        axes = _norm_axis(p, x.ndim)
        return fn(x, axis=axes, keepdims=bool(p.get("keepdims")))
    return run


for _name, _f in [("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
                  ("max", jnp.max), ("min", jnp.min),
                  ("nansum", jnp.nansum), ("nanprod", jnp.nanprod)]:
    register(_name, input_names=("data",), args=list(_REDUCE_ARGS),
             aliases=(_name + "_axis",))(_reduce(_f))


@register("norm", input_names=("data",),
          args=[Arg("ord", int, 2), Arg("axis", "shape", None),
                Arg("keepdims", bool, False)])
def _norm(p, x):
    axis = p.get("axis")
    axes = tuple(a % x.ndim for a in axis) if axis else None
    if p.get("ord", 2) == 1:
        return jnp.sum(jnp.abs(x), axis=axes, keepdims=bool(p.get("keepdims")))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=bool(p.get("keepdims"))))


def _arg_reduce(fn):
    def run(p, x):
        axis = p.get("axis")
        kd = bool(p.get("keepdims"))
        if axis is None:
            out = fn(x.reshape(-1), axis=0)
            return out.astype(x.dtype)
        out = fn(x, axis=int(axis[0]) if isinstance(axis, tuple) else int(axis))
        if kd:
            out = jnp.expand_dims(out, int(axis[0]) if isinstance(axis, tuple) else int(axis))
        return out.astype(jnp.float32)
    return run


register("argmax", input_names=("data",),
         args=[Arg("axis", int, None), Arg("keepdims", bool, False)],
         differentiable=False)(_arg_reduce(jnp.argmax))
register("argmin", input_names=("data",),
         args=[Arg("axis", int, None), Arg("keepdims", bool, False)],
         differentiable=False)(_arg_reduce(jnp.argmin))


@register("argmax_channel", input_names=("data",), differentiable=False)
def _argmax_channel(p, x):
    return jnp.argmax(x, axis=-1).astype(jnp.float32)


@register("topk", input_names=("data",),
          args=[Arg("axis", int, -1), Arg("k", int, 1), Arg("ret_typ", str, "indices"),
                Arg("is_ascend", bool, False), Arg("dtype", str, "float32")],
          differentiable=False)
def _topk(p, x):
    """Parity: src/operator/tensor/ordering_op.cc TopK."""
    axis = p["axis"] % x.ndim
    k = p["k"]
    xm = jnp.moveaxis(x, axis, -1)
    key = xm if p["is_ascend"] else -xm
    idx = jnp.argsort(key, axis=-1, stable=True)[..., :k]
    if p["ret_typ"] == "indices":
        return jnp.moveaxis(idx, -1, axis).astype(jnp.float32)
    vals = jnp.take_along_axis(xm, idx, axis=-1)
    if p["ret_typ"] == "value":
        return jnp.moveaxis(vals, -1, axis)
    # 'both' handled by frontend via two calls; 'mask' rare — approximate
    return jnp.moveaxis(vals, -1, axis)


@register("sort", input_names=("data",),
          args=[Arg("axis", int, -1), Arg("is_ascend", bool, True)])
def _sort(p, x):
    out = jnp.sort(x, axis=p["axis"])
    return out if p["is_ascend"] else jnp.flip(out, axis=p["axis"])


@register("argsort", input_names=("data",),
          args=[Arg("axis", int, -1), Arg("is_ascend", bool, True),
                Arg("dtype", str, "float32")],
          differentiable=False)
def _argsort(p, x):
    key = x if p["is_ascend"] else -x
    return jnp.argsort(key, axis=p["axis"], stable=True).astype(jnp.float32)
