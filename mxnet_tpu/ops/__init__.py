"""Operator library: importing this package registers every operator.

Parity map (SURVEY.md §2.2): elemwise/reduce/matrix ← src/operator/tensor/,
nn ← src/operator/nn/ + legacy root ops, init/random ← init_op.cc +
src/operator/random/, optimizer ← optimizer_op.cc, sequence+RNN ←
sequence_*.cc + rnn.cc, contrib ← src/operator/contrib/.
"""
from .registry import (OP_ALIASES, OP_REGISTRY, Operator, apply_op, get_op,
                       list_ops, make_vjp, register, zero_like_grad)
from . import elemwise
from . import reduce
from . import matrix
from . import nn
from . import init_ops
from . import random_ops
from . import optimizer_ops
from . import sequence
from . import compat
from . import vision
from . import contrib
from . import flash_attention
from . import custom
from . import sparse_ops
