"""Random sampling operators (parity: src/operator/random/).

The reference gives each op a per-device PRNG via ResourceRequest::kRandom
(include/mxnet/resource.h:37); here each sampling op receives an explicit
jax PRNG key (appended input, split from the framework-global key stream in
`mxnet_tpu.random`) — functional, reproducible, trace-safe.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import Arg, np_dtype
from .registry import register

_SHAPE_ARGS = [Arg("shape", "shape", ()), Arg("dtype", str, "float32"),
               Arg("ctx", str, None)]


def _shp(p):
    return p["shape"] or ()


@register("_random_uniform", input_names=(), needs_rng=True, differentiable=False,
          args=_SHAPE_ARGS + [Arg("low", float, 0.0), Arg("high", float, 1.0)],
          aliases=("uniform", "random_uniform"))
def _uniform(p, key):
    return jax.random.uniform(key, _shp(p), np_dtype(p["dtype"]), p["low"], p["high"])


@register("_random_normal", input_names=(), needs_rng=True, differentiable=False,
          args=_SHAPE_ARGS + [Arg("loc", float, 0.0), Arg("scale", float, 1.0)],
          aliases=("normal", "random_normal"))
def _normal(p, key):
    return p["loc"] + p["scale"] * jax.random.normal(key, _shp(p), np_dtype(p["dtype"]))


@register("_random_gamma", input_names=(), needs_rng=True, differentiable=False,
          args=_SHAPE_ARGS + [Arg("alpha", float, 1.0), Arg("beta", float, 1.0)],
          aliases=("random_gamma",))
def _gamma(p, key):
    return p["beta"] * jax.random.gamma(key, p["alpha"], _shp(p), np_dtype(p["dtype"]))


@register("_random_exponential", input_names=(), needs_rng=True, differentiable=False,
          args=_SHAPE_ARGS + [Arg("lam", float, 1.0)],
          aliases=("random_exponential",))
def _exponential(p, key):
    return jax.random.exponential(key, _shp(p), np_dtype(p["dtype"])) / p["lam"]


@register("_random_poisson", input_names=(), needs_rng=True, differentiable=False,
          args=_SHAPE_ARGS + [Arg("lam", float, 1.0)],
          aliases=("random_poisson",))
def _poisson(p, key):
    return jax.random.poisson(key, p["lam"], _shp(p)).astype(np_dtype(p["dtype"]))


@register("_random_negative_binomial", input_names=(), needs_rng=True,
          differentiable=False,
          args=_SHAPE_ARGS + [Arg("k", int, 1), Arg("p", float, 1.0)],
          aliases=("random_negative_binomial",))
def _neg_binomial(p, key):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, p["k"], _shp(p)) * (1 - p["p"]) / p["p"]
    return jax.random.poisson(k2, lam, _shp(p)).astype(np_dtype(p["dtype"]))


@register("_random_generalized_negative_binomial", input_names=(), needs_rng=True,
          differentiable=False,
          args=_SHAPE_ARGS + [Arg("mu", float, 1.0), Arg("alpha", float, 1.0)],
          aliases=("random_generalized_negative_binomial",))
def _gen_neg_binomial(p, key):
    k1, k2 = jax.random.split(key)
    a = 1.0 / max(p["alpha"], 1e-12)
    lam = jax.random.gamma(k1, a, _shp(p)) * p["mu"] / a
    return jax.random.poisson(k2, lam, _shp(p)).astype(np_dtype(p["dtype"]))


@register("_random_randint", input_names=(), needs_rng=True, differentiable=False,
          args=[Arg("low", int, 0), Arg("high", int, required=True),
                Arg("shape", "shape", ()), Arg("dtype", str, "int32"),
                Arg("ctx", str, None)],
          aliases=("random_randint",))
def _randint(p, key):
    return jax.random.randint(key, _shp(p), p["low"], p["high"],
                              np_dtype(p["dtype"]))


@register("_sample_multinomial", input_names=("data",), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("get_prob", bool, False),
                Arg("dtype", str, "int32")],
          aliases=("sample_multinomial",))
def _multinomial(p, data, key):
    n = 1
    for d in (p["shape"] or (1,)):
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        out = out.reshape(p["shape"] or ())
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
        out = out.reshape((data.shape[0],) + (p["shape"] or ()))
    return out.astype(np_dtype(p["dtype"]))


@register("_shuffle", input_names=("data",), needs_rng=True, differentiable=False,
          aliases=("shuffle",))
def _shuffle(p, data, key):
    return jax.random.permutation(key, data, axis=0)


# sample_* ops: per-element distribution parameters as tensor inputs
@register("_sample_uniform", input_names=("low", "high"), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_uniform",))
def _sample_uniform(p, low, high, key):
    shp = low.shape + (p["shape"] or ())
    u = jax.random.uniform(key, shp, np_dtype(p["dtype"]))
    bs = low.shape + (1,) * len(p["shape"] or ())
    return low.reshape(bs) + u * (high - low).reshape(bs)


@register("_sample_normal", input_names=("mu", "sigma"), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_normal",))
def _sample_normal(p, mu, sigma, key):
    shp = mu.shape + (p["shape"] or ())
    z = jax.random.normal(key, shp, np_dtype(p["dtype"]))
    bs = mu.shape + (1,) * len(p["shape"] or ())
    return mu.reshape(bs) + z * sigma.reshape(bs)


def _sample_bshape(p, param):
    """(param_shape + sample_shape, param broadcast shape) — parity:
    multisample_op.h: one batch of samples per distribution parameter."""
    shp = param.shape + (p["shape"] or ())
    return shp, param.shape + (1,) * len(p["shape"] or ())


@register("_sample_gamma", input_names=("alpha", "beta"), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_gamma",))
def _sample_gamma(p, alpha, beta, key):
    """Parity: sample_op.cc _sample_gamma — per-element (alpha, beta)."""
    shp, bs = _sample_bshape(p, alpha)
    g = jax.random.gamma(key, alpha.reshape(bs), shp)
    return (g * beta.reshape(bs)).astype(np_dtype(p["dtype"]))


@register("_sample_exponential", input_names=("lam",), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_exponential",))
def _sample_exponential(p, lam, key):
    shp, bs = _sample_bshape(p, lam)
    e = jax.random.exponential(key, shp)
    return (e / lam.reshape(bs)).astype(np_dtype(p["dtype"]))


@register("_sample_poisson", input_names=("lam",), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_poisson",))
def _sample_poisson(p, lam, key):
    shp, bs = _sample_bshape(p, lam)
    s = jax.random.poisson(key, jnp.broadcast_to(lam.reshape(bs), shp))
    return s.astype(np_dtype(p["dtype"]))


@register("_sample_negative_binomial", input_names=("k", "p"), needs_rng=True,
          differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_negative_binomial",))
def _sample_negative_binomial(p, k, prob, key):
    """NB(k, p) = Poisson(Gamma(k, (1-p)/p)) per element."""
    shp, bs = _sample_bshape(p, k)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k.astype(jnp.float32).reshape(bs), shp) * \
        ((1 - prob) / prob).reshape(bs)
    return jax.random.poisson(k2, lam).astype(np_dtype(p["dtype"]))


@register("_sample_generalized_negative_binomial", input_names=("mu", "alpha"),
          needs_rng=True, differentiable=False,
          args=[Arg("shape", "shape", ()), Arg("dtype", str, "float32")],
          aliases=("sample_generalized_negative_binomial",))
def _sample_gen_negative_binomial(p, mu, alpha, key):
    """GNB(mu, alpha) = Poisson(Gamma(1/alpha, mu*alpha)) per element."""
    shp, bs = _sample_bshape(p, mu)
    k1, k2 = jax.random.split(key)
    inv_a = (1.0 / jnp.maximum(alpha, 1e-12)).reshape(bs)
    lam = jax.random.gamma(k1, jnp.broadcast_to(inv_a, shp)) * \
        (mu * alpha).reshape(bs)
    return jax.random.poisson(k2, lam).astype(np_dtype(p["dtype"]))
