"""The `Custom` operator: user-defined python ops in symbol/ndarray graphs.

Reference parity: `src/operator/custom/custom.cc:37-79` (frontend-callback
op dispatched via MXCallbackList) + the user API contract in
`python/mxnet/operator.py:418-598` (CustomOp/CustomOpProp/register).

TPU-native realization: the user's numpy-level forward/backward run as host
callbacks through `jax.pure_callback`, so a Custom node embeds in fully
jitted executor/CachedOp graphs (XLA inserts the host transfer; everything
around it still fuses).  Gradients wire through `jax.custom_vjp` so
autograd/vjp sees the user's backward.  This is the documented escape hatch
— host callbacks cost a device→host→device round trip per step (SURVEY.md
§7 "hard parts": warn on perf).
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import Arg, MXNetError, ParamSchema
from .registry import OP_REGISTRY, Operator

# op_type -> CustomOpProp subclass (filled by mxnet_tpu.operator.register)
CUSTOM_PROP_REGISTRY: Dict[str, type] = {}

# (params incl __node__, shapes, dtypes) -> CustomOp instance.  The
# reference creates ONE operator per bound node (custom.cc CreateOp) and
# forward/backward share it — user ops stash intermediates on self in
# forward and read them in backward.  Symbol composition injects a unique
# `__node__` param so two identically-configured graph nodes never share
# state; eager nd.Custom calls (no node identity) share per signature.
# LRU-bounded so bucketing/reshape churn can't grow it unboundedly.
from collections import OrderedDict as _OrderedDict

_OP_INSTANCE_CACHE: "_OrderedDict" = _OrderedDict()
_OP_INSTANCE_CACHE_MAX = 256


def _get_op_instance(prop, pt, shapes, dtypes):
    key = (tuple(kv for kv in pt if kv[0] != "__is_train__"),
           tuple(tuple(s) for s in shapes),
           tuple(str(d) for d in dtypes))
    inst = _OP_INSTANCE_CACHE.get(key)
    if inst is None:
        inst = prop.create_operator(None, list(shapes), list(dtypes))
        _OP_INSTANCE_CACHE[key] = inst
        while len(_OP_INSTANCE_CACHE) > _OP_INSTANCE_CACHE_MAX:
            _OP_INSTANCE_CACHE.popitem(last=False)
    else:
        _OP_INSTANCE_CACHE.move_to_end(key)
    return inst


def _make_prop(pd):
    op_type = pd.get("op_type")
    cls = CUSTOM_PROP_REGISTRY.get(op_type)
    if cls is None:
        raise MXNetError(
            f"Custom op_type '{op_type}' not registered; use "
            "@mx.operator.register(name) on a CustomOpProp subclass")
    kwargs = {k: v for k, v in pd.items()
              if k != "op_type" and not k.startswith("__")}
    prop = cls(**kwargs)
    if prop.list_auxiliary_states():
        raise MXNetError(
            "Custom ops with auxiliary states are not supported on the "
            "TPU backend (declare them as regular arguments instead)")
    return prop


def _shapes_types(prop, ins):
    in_shapes = [tuple(x.shape) for x in ins]
    r = prop.infer_shape(list(in_shapes))
    in_shapes2, out_shapes = list(r[0]), list(r[1])
    in_types = [x.dtype for x in ins]
    rt = prop.infer_type(list(in_types))
    out_types = list(rt[1])
    return in_shapes2, out_shapes, in_types, out_types


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _custom_core(pt, *ins):
    outs, _ = _custom_fwd(pt, *ins)
    return outs


def _run_forward(pt, ins):
    pd = dict(pt)
    prop = _make_prop(pd)
    _, out_shapes, in_types, out_types = _shapes_types(prop, ins)
    is_train = bool(pd.get("__is_train__"))
    result = [jax.ShapeDtypeStruct(tuple(int(d) for d in s),
                                   _np.dtype(t))
              for s, t in zip(out_shapes, out_types)]

    def host_fwd(*arrs):
        from .. import ndarray as nd
        op = _get_op_instance(prop, pt, [a.shape for a in arrs],
                              [a.dtype for a in arrs])
        in_nd = [nd.array(_np.asarray(a)) for a in arrs]
        out_nd = [nd.zeros(tuple(int(d) for d in s), dtype=t)
                  for s, t in zip(out_shapes, out_types)]
        op.forward(is_train, ["write"] * len(out_nd), in_nd, out_nd, [])
        return tuple(o.asnumpy().astype(r.dtype).reshape(r.shape)
                     for o, r in zip(out_nd, result))

    outs = jax.pure_callback(host_fwd, tuple(result), *ins)
    return tuple(outs)


def _custom_fwd(pt, *ins):
    outs = _run_forward(pt, ins)
    return outs, (ins, outs)


def _custom_bwd(pt, res, gs):
    ins, outs = res
    pd = dict(pt)
    prop = _make_prop(pd)
    result = [jax.ShapeDtypeStruct(tuple(x.shape), _np.dtype(x.dtype))
              for x in ins]

    def host_bwd(*arrs):
        from .. import ndarray as nd
        n_in, n_out = len(ins), len(outs)
        in_arrs = arrs[:n_in]
        out_arrs = arrs[n_in:n_in + n_out]
        grad_arrs = arrs[n_in + n_out:]
        op = _get_op_instance(prop, pt, [a.shape for a in in_arrs],
                              [a.dtype for a in in_arrs])
        in_nd = [nd.array(_np.asarray(a)) for a in in_arrs]
        out_nd = [nd.array(_np.asarray(a)) for a in out_arrs]
        og_nd = [nd.array(_np.asarray(a)) for a in grad_arrs]
        ig_nd = [nd.zeros(tuple(x.shape), dtype=x.dtype) for x in in_nd]
        op.backward(["write"] * len(ig_nd), og_nd, in_nd, out_nd, ig_nd, [])
        return tuple(g.asnumpy().astype(r.dtype).reshape(r.shape)
                     for g, r in zip(ig_nd, result))

    grads = jax.pure_callback(host_bwd, tuple(result), *ins, *outs, *gs)
    return tuple(grads)


_custom_core.defvjp(_custom_fwd, _custom_bwd)


def _custom(p, *ins):
    """Parity: src/operator/custom/custom.cc — dispatch to the registered
    CustomOpProp's operator via host callback."""
    return _custom_core(tuple(sorted(p.items())), *ins)


def _custom_shape_hook(p, shapes):
    """Fill unknown input shapes (e.g. the label variable) from the prop's
    infer_shape — the reference relies on this for Custom loss layers
    (custom_softmax.py infers label_shape from data_shape)."""
    known = [tuple(s) if s is not None else () for s in shapes]
    try:
        prop = _make_prop(dict(p))
        corrected = list(prop.infer_shape(list(known))[0])
    except Exception:
        return {}
    return {i: tuple(int(d) for d in corrected[i])
            for i in range(len(shapes))
            if shapes[i] is None and i < len(corrected) and corrected[i]}


def custom_num_outputs(params) -> int:
    prop = _make_prop(dict(params))
    return len(prop.list_outputs())


# registered directly (open schema: user kwargs pass through as strings)
_custom_op = Operator(
    name="Custom",
    fn=_custom,
    input_names=["args"],
    schema=ParamSchema([Arg("op_type", str, required=True)],
                       open_schema=True),
    num_outputs=-1,
    variadic=True,
    takes_is_train=True,
    docstring=_custom.__doc__ or "",
)
OP_REGISTRY["Custom"] = _custom_op
