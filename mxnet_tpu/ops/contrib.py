"""Contrib operators (parity: src/operator/contrib/ — SURVEY.md §2.2).

ctc_loss (optax XLA), fft/ifft (cuFFT → jnp.fft), quantize/dequantize,
count_sketch, MultiBoxPrior/Target/Detection (SSD detection ops — the
reference's hand-written CUDA kernels become vectorized jax; non-max
suppression uses a fixed-iteration lax loop, XLA-compilable).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import Arg, MXNetError
from .registry import register


@register("_contrib_ctc_loss", input_names=("data", "label", "data_lengths",
                                            "label_lengths"),
          aliases=("ctc_loss", "CTCLoss"),
          args=[Arg("use_data_lengths", bool, False),
                Arg("use_label_lengths", bool, False),
                Arg("blank_label", str, "first")])
def _ctc_loss(p, data, label, data_lengths=None, label_lengths=None):
    """Parity: contrib/ctc_loss.cc.  data: (T, N, C) activations (pre-softmax),
    label: (N, L) padded with 0/-1; optional per-sequence lengths gated by
    use_data_lengths / use_label_lengths (reference inputs 3 and 4)."""
    import optax
    if (p["use_label_lengths"] and not p["use_data_lengths"]
            and label_lengths is None):
        # positional call with the unused data_lengths slot elided (symbol
        # graphs bind inputs positionally; the slot list is gated on the
        # use_* flags) — the third input IS label_lengths
        data_lengths, label_lengths = None, data_lengths
    T, N, C = data.shape
    logits = jnp.transpose(data, (1, 0, 2))  # (N,T,C)
    labels = label.astype(jnp.int32)
    logit_pad = jnp.zeros((N, T), jnp.float32)
    if p["use_data_lengths"] and data_lengths is not None:
        steps = jnp.arange(T)[None, :]
        logit_pad = (steps >= data_lengths[:, None]).astype(jnp.float32)
    if p["blank_label"] == "first":
        # mxnet 'first': channel 0 is blank, real labels are 1..C-1 —
        # matches optax blank_id=0 with labels kept as-is
        lab_valid = labels > 0
        blank = 0
    else:
        lab_valid = labels >= 0
        blank = C - 1
    if p["use_label_lengths"] and label_lengths is not None:
        steps = jnp.arange(labels.shape[1])[None, :]
        lab_valid = steps < label_lengths[:, None].astype(jnp.int32)
    lab = jnp.where(lab_valid, labels, 0)
    return optax.ctc_loss(logits, logit_pad, lab,
                          (~lab_valid).astype(jnp.float32), blank_id=blank)


@register("_contrib_fft", input_names=("data",), aliases=("fft",),
          args=[Arg("compute_size", int, 128)])
def _fft(p, x):
    """Parity: contrib/fft.cc — output interleaves real/imag on last dim."""
    out = jnp.fft.fft(x, axis=-1)
    return jnp.stack([out.real, out.imag], axis=-1).reshape(
        x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)


@register("_contrib_ifft", input_names=("data",), aliases=("ifft",),
          args=[Arg("compute_size", int, 128)])
def _ifft(p, x):
    n = x.shape[-1] // 2
    comp = x.reshape(x.shape[:-1] + (n, 2))
    z = comp[..., 0] + 1j * comp[..., 1]
    return jnp.fft.ifft(z, axis=-1).real.astype(x.dtype) * n


@register("_contrib_quantize", input_names=("data", "min_range", "max_range"),
          num_outputs=3, differentiable=False,
          args=[Arg("out_type", str, "uint8")])
def _quantize(p, data, min_range, max_range):
    """Parity: contrib/quantize.cc — affine quantization to uint8/int8."""
    if p["out_type"] == "uint8":
        qmin, qmax, dt = 0.0, 255.0, jnp.uint8
    else:
        qmin, qmax, dt = -127.0, 127.0, jnp.int8
    scale = (qmax - qmin) / jnp.maximum(max_range - min_range, 1e-8)
    q = jnp.clip(jnp.round((data - min_range) * scale + qmin), qmin, qmax)
    return q.astype(dt), min_range, max_range


@register("_contrib_dequantize", input_names=("data", "min_range", "max_range"),
          differentiable=False, args=[Arg("out_type", str, "float32")])
def _dequantize(p, data, min_range, max_range):
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    else:
        qmin, qmax = -127.0, 127.0
    scale = (max_range - min_range) / (qmax - qmin)
    return (data.astype(jnp.float32) - qmin) * scale + min_range


@register("_contrib_count_sketch", input_names=("data", "h", "s"),
          args=[Arg("out_dim", int, required=True),
                Arg("processing_batch_size", int, 32)])
def _count_sketch(p, data, h, s):
    """Parity: contrib/count_sketch.cc — random-projection sketch."""
    n, d = data.shape
    out_dim = p["out_dim"]
    hh = h.reshape(-1).astype(jnp.int32)[:d]
    ss = s.reshape(-1)[:d]
    vals = data * ss[None, :]
    out = jnp.zeros((n, out_dim), data.dtype)
    return out.at[:, hh].add(vals)


# ---------------------------------------------------------------------------
# SSD multibox ops (parity: src/operator/contrib/multibox_*.cc)
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", input_names=("data",),
          aliases=("MultiBoxPrior",), differentiable=False,
          args=[Arg("sizes", "floats", (1.0,)), Arg("ratios", "floats", (1.0,)),
                Arg("clip", bool, False), Arg("steps", "floats", (-1.0, -1.0)),
                Arg("offsets", "floats", (0.5, 0.5))])
def _multibox_prior(p, data):
    """Anchor generation (parity: multibox_prior.cc).  data: (N,C,H,W) →
    (1, H*W*num_anchors, 4) corner-format anchors in [0,1]."""
    H, W = data.shape[2], data.shape[3]
    sizes = [float(s) for s in p["sizes"]]
    ratios = [float(r) for r in p["ratios"]]
    step_y, step_x = p["steps"]
    step_y = 1.0 / H if step_y <= 0 else step_y
    step_x = 1.0 / W if step_x <= 0 else step_x
    off_y, off_x = p["offsets"]
    cy = (jnp.arange(H) + off_y) * step_y
    cx = (jnp.arange(W) + off_x) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1).reshape(-1, 2)
    whs = []
    # mxnet convention: sizes[0] with each ratio? No — (size,1.0) for each
    # size + (sizes[0], ratio) for each extra ratio → len(sizes)+len(ratios)-1
    for s in sizes:
        whs.append((s * (H / W) ** 0.5 if False else s, s))
    base = sizes[0]
    for r in ratios[1:]:
        whs.append((base * (r ** 0.5), base / (r ** 0.5)))
    whs = jnp.asarray(whs)  # (A, 2) = (w, h)
    A = whs.shape[0]
    centers = jnp.repeat(cyx, A, axis=0)  # (H*W*A, 2) [cy, cx]
    wh = jnp.tile(whs, (H * W, 1))
    xmin = centers[:, 1] - wh[:, 0] / 2
    ymin = centers[:, 0] - wh[:, 1] / 2
    xmax = centers[:, 1] + wh[:, 0] / 2
    ymax = centers[:, 0] + wh[:, 1] / 2
    out = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)
    if p["clip"]:
        out = jnp.clip(out, 0.0, 1.0)
    return out[None]


def _iou_corner(a, b):
    """IoU between (...,4) corner boxes a and b."""
    ix1 = jnp.maximum(a[..., 0], b[..., 0])
    iy1 = jnp.maximum(a[..., 1], b[..., 1])
    ix2 = jnp.minimum(a[..., 2], b[..., 2])
    iy2 = jnp.minimum(a[..., 3], b[..., 3])
    iw = jnp.maximum(ix2 - ix1, 0)
    ih = jnp.maximum(iy2 - iy1, 0)
    inter = iw * ih
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-10)


@register("_contrib_MultiBoxTarget",
          input_names=("anchor", "label", "cls_pred"),
          aliases=("MultiBoxTarget",), num_outputs=3, differentiable=False,
          args=[Arg("overlap_threshold", float, 0.5),
                Arg("ignore_label", float, -1.0),
                Arg("negative_mining_ratio", float, -1.0),
                Arg("negative_mining_thresh", float, 0.5),
                Arg("minimum_negative_samples", int, 0),
                Arg("variances", "floats", (0.1, 0.1, 0.2, 0.2))])
def _multibox_target(p, anchor, label, cls_pred):
    """Anchor→GT matching + regression targets (parity: multibox_target.cc).

    anchor: (1,A,4); label: (N,M,5) [cls,x1,y1,x2,y2] (cls<0 = pad);
    cls_pred: (N, num_cls+1, A).  Returns (loc_target (N,A*4),
    loc_mask (N,A*4), cls_target (N,A))."""
    anchors = anchor[0]  # (A,4)
    A = anchors.shape[0]
    vx, vy, vw, vh = p["variances"]
    thresh = p["overlap_threshold"]

    def per_sample(lab):
        valid = lab[:, 0] >= 0  # (M,)
        gt = lab[:, 1:5]
        ious = _iou_corner(anchors[:, None, :], gt[None, :, :])  # (A,M)
        ious = jnp.where(valid[None, :], ious, -1.0)
        best_gt = jnp.argmax(ious, axis=1)           # (A,)
        best_iou = jnp.max(ious, axis=1)
        matched = best_iou > thresh
        # ensure every valid gt owns its argmax anchor
        best_anchor = jnp.argmax(ious, axis=0)       # (M,)
        forced = jnp.zeros(A, bool).at[best_anchor].set(valid)
        forced_gt = jnp.zeros(A, jnp.int32).at[best_anchor].set(
            jnp.arange(gt.shape[0], dtype=jnp.int32))
        use_gt = jnp.where(forced, forced_gt, best_gt)
        matched = matched | forced
        g = gt[use_gt]
        # encode (corner→center) with variances
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / vx
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / vy
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / vw
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / vh
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)  # (A,4)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.where(matched[:, None],
                          jnp.ones((A, 4)), 0.0).reshape(-1)
        cls_t = jnp.where(matched, lab[use_gt, 0] + 1, 0.0)
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection",
          input_names=("cls_prob", "loc_pred", "anchor"),
          aliases=("MultiBoxDetection",), differentiable=False,
          args=[Arg("clip", bool, True), Arg("threshold", float, 0.01),
                Arg("background_id", int, 0), Arg("nms_threshold", float, 0.5),
                Arg("force_suppress", bool, False),
                Arg("variances", "floats", (0.1, 0.1, 0.2, 0.2)),
                Arg("nms_topk", int, -1)])
def _multibox_detection(p, cls_prob, loc_pred, anchor):
    """Decode + NMS (parity: multibox_detection.cc).  Returns
    (N, A, 6) rows [cls_id, score, x1, y1, x2, y2]; suppressed rows cls=-1."""
    anchors = anchor[0]
    A = anchors.shape[0]
    vx, vy, vw, vh = p["variances"]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def per_sample(probs, locs):
        loc = locs.reshape(A, 4)
        cx = loc[:, 0] * vx * aw + acx
        cy = loc[:, 1] * vy * ah + acy
        w = jnp.exp(loc[:, 2] * vw) * aw
        h = jnp.exp(loc[:, 3] * vh) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if p["clip"]:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # class scores, excluding background
        scores = probs[1:] if p["background_id"] == 0 else \
            jnp.concatenate([probs[:p["background_id"]],
                             probs[p["background_id"] + 1:]])
        cls_id = jnp.argmax(scores, axis=0).astype(jnp.float32)  # (A,)
        score = jnp.max(scores, axis=0)
        keep = score > p["threshold"]
        cls_id = jnp.where(keep, cls_id, -1.0)
        # greedy NMS, fixed iterations over score-sorted order
        order = jnp.argsort(-score)
        boxes_s = boxes[order]
        cls_s = cls_id[order]
        score_s = score[order]
        alive = cls_s >= 0

        def body(i, alive):
            box_i = boxes_s[i]
            cls_i = cls_s[i]
            this_alive = alive[i]
            ious = _iou_corner(box_i[None], boxes_s)
            same = (cls_s == cls_i) | bool(p["force_suppress"])
            sup = (ious > p["nms_threshold"]) & same & \
                (jnp.arange(A) > i) & this_alive
            return alive & ~sup

        alive = lax.fori_loop(0, A, body, alive)
        out = jnp.concatenate(
            [jnp.where(alive, cls_s, -1.0)[:, None], score_s[:, None],
             boxes_s], axis=1)
        return out

    return jax.vmap(per_sample)(cls_prob, loc_pred.reshape(
        cls_prob.shape[0], -1))


# ---------------------------------------------------------------------------
# RPN proposals (parity: src/operator/contrib/proposal.cc / multi_proposal.cc)
# ---------------------------------------------------------------------------
def _gen_base_anchors(scales, ratios, base_size):
    """Anchors centered at (base/2, base/2), corner format, in pixels."""
    anchors = []
    cx = cy = (base_size - 1) / 2.0
    area = float(base_size * base_size)
    for r in ratios:
        w = round((area / r) ** 0.5)
        h = round(w * r)
        for s in scales:
            ws, hs = w * s, h * s
            anchors.append([cx - (ws - 1) / 2, cy - (hs - 1) / 2,
                            cx + (ws - 1) / 2, cy + (hs - 1) / 2])
    return jnp.asarray(anchors, jnp.float32)


@register("_contrib_Proposal", input_names=("cls_prob", "bbox_pred", "im_info"),
          aliases=("Proposal", "_contrib_MultiProposal", "MultiProposal"),
          differentiable=False,
          args=[Arg("rpn_pre_nms_top_n", int, 6000),
                Arg("rpn_post_nms_top_n", int, 300),
                Arg("threshold", float, 0.7),
                Arg("rpn_min_size", int, 16),
                Arg("scales", "floats", (4.0, 8.0, 16.0, 32.0)),
                Arg("ratios", "floats", (0.5, 1.0, 2.0)),
                Arg("feature_stride", int, 16),
                Arg("output_score", bool, False),
                Arg("iou_loss", bool, False)])
def _proposal(p, cls_prob, bbox_pred, im_info):
    """RPN proposal generation (parity: proposal.cc behavior): decode
    per-anchor bbox deltas, clip to image, filter small boxes, NMS, take
    top-k.  Static shapes: output (N * post_nms_top_n, 5) rois
    [batch_idx, x1, y1, x2, y2], padded by repeating the best roi."""
    N, _, H, W = cls_prob.shape
    stride = p["feature_stride"]
    base = _gen_base_anchors(p["scales"], p["ratios"], stride)  # (A,4)
    A = base.shape[0]
    shift_x = jnp.arange(W) * stride
    shift_y = jnp.arange(H) * stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)  # (H,W)
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 4)
    anchors = (base[None, :, :] + shifts[:, None, :]).reshape(-1, 4)  # (H*W*A,4)
    K = anchors.shape[0]
    pre_n = min(p["rpn_pre_nms_top_n"], K)
    post_n = p["rpn_post_nms_top_n"]

    def per_image(scores_hw, deltas_hw, info):
        # scores: (2A,H,W) → fg scores (A,H,W) → (H*W*A,)
        fg = scores_hw[A:].transpose(1, 2, 0).reshape(-1)
        d = deltas_hw.transpose(1, 2, 0).reshape(-1, 4)  # (H*W*A,4)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        acx = anchors[:, 0] + 0.5 * (aw - 1)
        acy = anchors[:, 1] + 0.5 * (ah - 1)
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        x1 = jnp.clip(cx - 0.5 * (w - 1), 0, info[1] - 1)
        y1 = jnp.clip(cy - 0.5 * (h - 1), 0, info[0] - 1)
        x2 = jnp.clip(cx + 0.5 * (w - 1), 0, info[1] - 1)
        y2 = jnp.clip(cy + 0.5 * (h - 1), 0, info[0] - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        min_size = p["rpn_min_size"] * info[2]
        valid = ((x2 - x1 + 1) >= min_size) & ((y2 - y1 + 1) >= min_size)
        fg = jnp.where(valid, fg, -1.0)
        order = jnp.argsort(-fg)[:pre_n]
        boxes_s = boxes[order]
        score_s = fg[order]
        alive = score_s > -1.0

        def iou_pixel(a, b):
            # proposal.cc integer-pixel convention: width = x2 - x1 + 1
            ix1 = jnp.maximum(a[..., 0], b[..., 0])
            iy1 = jnp.maximum(a[..., 1], b[..., 1])
            ix2 = jnp.minimum(a[..., 2], b[..., 2])
            iy2 = jnp.minimum(a[..., 3], b[..., 3])
            inter = jnp.maximum(ix2 - ix1 + 1, 0) * \
                jnp.maximum(iy2 - iy1 + 1, 0)
            area_a = (a[..., 2] - a[..., 0] + 1) * (a[..., 3] - a[..., 1] + 1)
            area_b = (b[..., 2] - b[..., 0] + 1) * (b[..., 3] - b[..., 1] + 1)
            return inter / jnp.maximum(area_a + area_b - inter, 1e-10)

        def body(i, alive):
            ious = iou_pixel(boxes_s[i][None], boxes_s)
            sup = (ious > p["threshold"]) & (jnp.arange(pre_n) > i) & alive[i]
            return alive & ~sup

        alive = lax.fori_loop(0, pre_n, body, alive)
        rank = jnp.where(alive, jnp.arange(pre_n), pre_n)
        keep = jnp.argsort(rank)[:post_n]
        kept_boxes = boxes_s[keep]
        kept_scores = jnp.where(alive[keep], score_s[keep], 0.0)
        # pad slots past the kept count with the top roi (reference pads too)
        pad_mask = (jnp.arange(post_n) < alive.sum())[:, None]
        kept_boxes = jnp.where(pad_mask, kept_boxes, kept_boxes[0])
        return kept_boxes, kept_scores

    boxes, scores = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=jnp.float32), post_n)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(-1, 4)], axis=1)
    if p["output_score"]:
        return rois, scores.reshape(-1, 1)
    return rois


# ---------------------------------------------------------------------------
# Deformable ops (parity: src/operator/contrib/deformable_convolution.cc,
# deformable_psroi_pooling.cc) — bilinear sampling via map_coordinates
# ---------------------------------------------------------------------------
@register("_contrib_DeformableConvolution",
          input_names=("data", "offset", "weight", "bias"),
          aliases=("DeformableConvolution",),
          args=[Arg("kernel", "shape", required=True),
                Arg("stride", "shape", (1, 1)), Arg("dilate", "shape", (1, 1)),
                Arg("pad", "shape", (0, 0)), Arg("num_filter", int, required=True),
                Arg("num_group", int, 1), Arg("num_deformable_group", int, 1),
                Arg("no_bias", bool, False)])
def _deformable_conv(p, data, offset, weight, bias=None):
    """Deformable conv v1: per-position sampling offsets bend the kernel
    grid; bilinear-sampled columns contract with the weight on the MXU."""
    kh, kw = p["kernel"]
    sh, sw = p["stride"] or (1, 1)
    dh, dw = p["dilate"] or (1, 1)
    ph, pw = p["pad"] or (0, 0)
    N, C, H, W = data.shape
    G = p["num_deformable_group"]
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    def sample_image(img, off):
        # img: (C,H,W); off: (2*G*kh*kw, Ho, Wo) with the reference's
        # interleaved layout: channel 2*(i*kw+j) = y, 2*(i*kw+j)+1 = x
        # (deformable_im2col convention)
        off = off.reshape(G, kh * kw, 2, Ho, Wo)
        from jax.scipy.ndimage import map_coordinates

        def sample_channel(ch_img, oy, ox):
            yy = (jnp.arange(Ho)[None, None, :, None] * sh - ph +
                  jnp.arange(kh)[:, None, None, None] * dh + oy)
            xx = (jnp.arange(Wo)[None, None, None, :] * sw - pw +
                  jnp.arange(kw)[None, :, None, None] * dw + ox)
            samp = map_coordinates(ch_img, [yy.reshape(-1), xx.reshape(-1)],
                                   order=1, mode="constant", cval=0.0)
            return samp.reshape(kh, kw, Ho, Wo)

        per_g = C // G
        groups = []
        for g in range(G):  # G is small; channels within a group vmap
            oy = off[g, :, 0].reshape(kh, kw, Ho, Wo)
            ox = off[g, :, 1].reshape(kh, kw, Ho, Wo)
            block = img[g * per_g:(g + 1) * per_g]
            groups.append(jax.vmap(sample_channel, in_axes=(0, None, None))(
                block, oy, ox))
        return jnp.concatenate(groups)  # (C,kh,kw,Ho,Wo)

    cols = jax.vmap(sample_image)(data, offset)  # (N,C,kh,kw,Ho,Wo)
    ng = p["num_group"]
    Cg = C // ng
    Fg = p["num_filter"] // ng
    cols = cols.reshape(N, ng, Cg, kh, kw, Ho, Wo)
    wgt = weight.reshape(ng, Fg, Cg, kh, kw)
    out = jnp.einsum("ngcijhw,gfcij->ngfhw", cols, wgt)
    out = out.reshape(N, p["num_filter"], Ho, Wo)
    if not p["no_bias"] and bias is not None:
        out = out + bias[None, :, None, None]
    return out


@register("khatri_rao", input_names=("args",), variadic=True)
def _khatri_rao(p, *mats):
    """Column-wise Khatri-Rao product (parity: src/operator/contrib/
    krprod.h — per-column Kronecker products): inputs (r_i, k) with a
    shared column count k → output (prod r_i, k)."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, m.shape[1])
    return out


@register("_contrib_DeformablePSROIPooling",
          input_names=("data", "rois", "trans"),
          aliases=("DeformablePSROIPooling",),
          args=[Arg("spatial_scale", float, required=True),
                Arg("output_dim", int, required=True),
                Arg("group_size", int, required=True),
                Arg("pooled_size", int, required=True),
                Arg("part_size", int, 0),
                Arg("sample_per_part", int, 4),
                Arg("trans_std", float, 0.0),
                Arg("no_trans", bool, False)])
def _deformable_psroi_pooling(p, data, rois, trans=None):
    """Deformable position-sensitive ROI pooling (parity:
    src/operator/contrib/deformable_psroi_pooling.cc): each pooled cell's
    sampling window shifts by a learned per-part offset
    trans[(cls*2[+1]), part_h, part_w] * trans_std * roi_size; samples
    falling outside the image are excluded from the bin average (masked
    mean).  Differentiable through the bilinear sampling and the offsets.
    """
    k = p["pooled_size"]
    D = p["output_dim"]
    gs = p["group_size"] or k
    ps = p["part_size"] or k
    S = p["sample_per_part"]
    scale = p["spatial_scale"]
    no_trans = p["no_trans"] or trans is None
    tstd = p["trans_std"]
    N, C, H, W = data.shape
    ncls = 1 if no_trans else trans.shape[1] // 2
    per_cls = D // ncls
    from jax.scipy.ndimage import map_coordinates

    def per_roi(roi, tr):
        b = roi[0].astype(jnp.int32)
        # reference rounds roi coords then offsets by half a pixel
        x1 = jnp.round(roi[1]) * scale - 0.5
        y1 = jnp.round(roi[2]) * scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / k, rh / k
        sub_w, sub_h = bw / S, bh / S
        img = data[b]

        def pool_channel(d):
            cls = d // per_cls

            def cell(i, j):
                if no_trans:
                    dx = dy = 0.0
                else:
                    pi = i * ps // k
                    pj = j * ps // k
                    dx = tr[cls * 2, pi, pj] * tstd * rw
                    dy = tr[cls * 2 + 1, pi, pj] * tstd * rh
                ws = j * bw + x1 + dx
                hs = i * bh + y1 + dy
                # reference kernel samples at sub-bin LEFT edges
                # (deformable_psroi_pooling.cu: w = wstart + iw*sub_bin)
                sx = ws + jnp.arange(S) * sub_w
                sy = hs + jnp.arange(S) * sub_h
                gy = jnp.repeat(sy, S)
                gx = jnp.tile(sx, S)
                valid = ((gx > -0.5) & (gx < W - 0.5) &
                         (gy > -0.5) & (gy < H - 0.5))
                gh = i * gs // k
                gw = j * gs // k
                ch = (d * gs + gh) * gs + gw
                vals = map_coordinates(img[ch],
                                       [jnp.clip(gy, 0, H - 1),
                                        jnp.clip(gx, 0, W - 1)],
                                       order=1, mode="nearest")
                cnt = jnp.maximum(valid.sum(), 1)
                return jnp.where(valid, vals, 0.0).sum() / cnt

            return jnp.stack([jnp.stack([cell(i, j) for j in range(k)])
                              for i in range(k)])

        return jnp.stack([pool_channel(d) for d in range(D)])

    if no_trans:
        tr0 = jnp.zeros((rois.shape[0], 2, ps, ps), data.dtype)
    else:
        tr0 = trans
    return jax.vmap(per_roi)(rois, tr0)


@register("_contrib_PSROIPooling", input_names=("data", "rois"),
          aliases=("PSROIPooling",),
          args=[Arg("spatial_scale", float, required=True),
                Arg("output_dim", int, required=True),
                Arg("pooled_size", int, required=True),
                Arg("group_size", int, 0)])
def _psroi_pooling(p, data, rois):
    """Position-sensitive ROI pooling (R-FCN): score-map channel
    (ctop*gs+gh)*gs+gw selected per output cell (gh/gw = the cell's group),
    average-pooled within each bin; differentiable through the bilinear
    sampling (the reference implements an explicit backward)."""
    k = p["pooled_size"]
    D = p["output_dim"]
    gs = p["group_size"] or k
    scale = p["spatial_scale"]
    N, C, H, W = data.shape

    def per_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * scale, roi[2] * scale, \
            roi[3] * scale, roi[4] * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / k, rh / k
        S = 4  # samples per bin edge
        ys = y1 + (jnp.arange(k)[:, None] + (jnp.arange(S)[None, :] + 0.5) / S) * bin_h
        xs = x1 + (jnp.arange(k)[:, None] + (jnp.arange(S)[None, :] + 0.5) / S) * bin_w
        yy = jnp.clip(ys, 0, H - 1)
        xx = jnp.clip(xs, 0, W - 1)
        from jax.scipy.ndimage import map_coordinates
        img = data[b]  # (C,H,W)

        def pool_channel(d):
            # channel for output d, cell (i,j): group (gh,gw) = bucketed
            # cell position; ch = (d*gs + gh)*gs + gw (psroi_pooling.cc)
            def cell(i, j):
                gh = i * gs // k
                gw = j * gs // k
                ch = (d * gs + gh) * gs + gw
                grid_y = jnp.repeat(yy[i], S)
                grid_x = jnp.tile(xx[j], S)
                vals = map_coordinates(img[ch], [grid_y, grid_x], order=1,
                                       mode="nearest")
                return vals.mean()
            return jnp.stack([jnp.stack([cell(i, j) for j in range(k)])
                              for i in range(k)])

        return jnp.stack([pool_channel(d) for d in range(D)])  # (D,k,k)

    return jax.vmap(per_roi)(rois)
