"""Elementwise operators (unary, binary broadcast, scalar variants).

Reference parity: `src/operator/tensor/elemwise_unary_op*.cc`,
`elemwise_binary_{op,broadcast_op}*.cc`, `elemwise_scalar_op*.cc`, and the
mshadow functor zoo (`src/operator/mshadow_op.h:53-69`).  On TPU each of
these is one XLA HLO; fusion with neighbors is automatic under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp

from ..base import Arg
from .registry import register

# ---------------------------------------------------------------------------
# Unary ops (parity: elemwise_unary_op.cc registrations)
# ---------------------------------------------------------------------------
_F32 = jnp.float32


def _softrelu(x):
    return jnp.logaddexp(x, 0.0)


def _softsign(x):
    return x / (1 + jnp.abs(x))


_UNARY = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": _softsign,
    "negative": jnp.negative,
    "reciprocal": lambda x: 1.0 / x,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: 1.0 / jnp.sqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "gamma": lambda x: jnp.exp(jsp.gammaln(x)),
    "gammaln": jsp.gammaln,
    "erf": jsp.erf,
    "erfinv": jsp.erfinv,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "logical_not": lambda x: (x == 0).astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else _F32),
}

for _name, _f in _UNARY.items():
    register(_name, input_names=("data",))(
        (lambda f: lambda p, x: f(x))(_f))

register("softrelu", input_names=("data",))(lambda p, x: _softrelu(x))


@register("_copy", input_names=("data",), aliases=("identity",))
def _copy(p, x):
    return x


@register("BlockGrad", input_names=("data",), aliases=("stop_gradient",))
def _block_grad(p, x):
    """Parity: src/operator/tensor/elemwise_unary_op.cc BlockGrad."""
    return jax.lax.stop_gradient(x)


@register("make_loss", input_names=("data",))
def _make_loss_op(p, x):
    return x


@register("clip", input_names=("data",),
          args=[Arg("a_min", float, required=True), Arg("a_max", float, required=True)])
def _clip(p, x):
    return jnp.clip(x, p["a_min"], p["a_max"])


@register("Cast", input_names=("data",), aliases=("cast",),
          args=[Arg("dtype", str, required=True)])
def _cast(p, x):
    from ..base import np_dtype
    return x.astype(np_dtype(p["dtype"]))


# ---------------------------------------------------------------------------
# Binary broadcast + same-shape elemwise (parity: elemwise_binary_broadcast_op)
# ---------------------------------------------------------------------------
def _bool_out(f):
    return lambda a, b: f(a, b).astype(jnp.result_type(a, b))


_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": _bool_out(jnp.equal),
    "not_equal": _bool_out(jnp.not_equal),
    "greater": _bool_out(jnp.greater),
    "greater_equal": _bool_out(jnp.greater_equal),
    "lesser": _bool_out(jnp.less),
    "lesser_equal": _bool_out(jnp.less_equal),
    "logical_and": _bool_out(lambda a, b: (a != 0) & (b != 0)),
    "logical_or": _bool_out(lambda a, b: (a != 0) | (b != 0)),
    "logical_xor": _bool_out(lambda a, b: (a != 0) ^ (b != 0)),
}

_ELEMWISE_ALIAS = {"add": ("elemwise_add", "_plus"), "sub": ("elemwise_sub", "_minus"),
                   "mul": ("elemwise_mul",), "div": ("elemwise_div",)}

for _name, _f in _BINARY.items():
    register("broadcast_" + _name, input_names=("lhs", "rhs"),
             aliases=_ELEMWISE_ALIAS.get(_name, ()))(
        (lambda f: lambda p, a, b: f(a, b))(_f))

# scalar variants (parity: *_scalar ops, used by NDArray __add__ etc.)
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(jnp.full_like(x, s), x) if False else jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}

for _name, _f in _SCALAR.items():
    register(_name, input_names=("data",), args=[Arg("scalar", float, required=True)])(
        (lambda f: lambda p, x: f(x, p["scalar"]))(_f))


@register("add_n", input_names=("args",), variadic=True,
          aliases=("ElementWiseSum", "_sum"))
def _add_n(p, *xs):
    """Parity: src/operator/tensor/elemwise_sum.cc."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register("smooth_l1", input_names=("data",), args=[Arg("scalar", float, 1.0)])
def _smooth_l1(p, x):
    s2 = p["scalar"] ** 2
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * jnp.square(x), absx - 0.5 / s2)
