"""Neural-network layer operators, lowered to XLA (MXU-targeted).

Reference parity: `src/operator/nn/` (FullyConnected, Convolution,
Deconvolution, Pooling, BatchNorm, softmax, Dropout, Activation — 33 files of
mshadow/cuDNN kernels) plus legacy root ops (LeakyReLU, LRN, InstanceNorm,
L2Normalization, UpSampling, SoftmaxOutput, regression outputs, MakeLoss,
SVMOutput).  Conv/matmul map directly onto the MXU via
`lax.conv_general_dilated`/`jnp.matmul`; the cuDNN algo-autotuning layer
(`src/operator/nn/cudnn/`) has no analog because XLA picks conv algorithms.
"""
from __future__ import annotations

import functools
import itertools as _itertools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import Arg, MXNetError
from .. import layout as _layout
from .registry import register


# ---------------------------------------------------------------------------
# FullyConnected (parity: src/operator/nn/fully_connected-inl.h:69)
# ---------------------------------------------------------------------------
@register("FullyConnected", input_names=("data", "weight", "bias"),
          args=[Arg("num_hidden", int, required=True), Arg("no_bias", bool, False),
                Arg("flatten", bool, True)])
def _fully_connected(p, data, weight, bias=None):
    x = data.reshape(data.shape[0], -1) if p["flatten"] else data
    out = jnp.matmul(x, weight.T)
    if not p["no_bias"]:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------
def _conv_dims(kernel):
    n = len(kernel)
    if n == 1:
        return ("NCH", "OIH", "NCH")
    if n == 2:
        return ("NCHW", "OIHW", "NCHW")
    if n == 3:
        return ("NCDHW", "OIDHW", "NCDHW")
    raise MXNetError(f"unsupported conv kernel rank {n}")


def _conv_dims_cl(kernel):
    """Channels-last dimension numbers (mxnet_tpu.layout NHWC mode): the
    TPU-native form — channel on the minor (lane) axis, no internal
    transposes from XLA's conv emitter."""
    n = len(kernel)
    if n == 1:
        return ("NWC", "WIO", "NWC")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC")
    if n == 3:
        return ("NDHWC", "DHWIO", "NDHWC")
    raise MXNetError(f"unsupported conv kernel rank {n}")


def _w_to_cl(w, n):
    """OI[spatial] kernel → [spatial]IO (constant-folded per step)."""
    return w.transpose(tuple(range(2, n + 2)) + (1, 0))


def _tup(v, n, default=1):
    if not v:
        return (default,) * n
    return v if len(v) == n else tuple(v) * n


@register("Convolution", input_names=("data", "weight", "bias"),
          aliases=("Convolution_v1",),
          args=[Arg("kernel", "shape", required=True), Arg("stride", "shape", ()),
                Arg("dilate", "shape", ()), Arg("pad", "shape", ()),
                Arg("num_filter", int, required=True), Arg("num_group", int, 1),
                Arg("no_bias", bool, False), Arg("layout", str, None),
                Arg("workspace", int, 1024), Arg("cudnn_tune", str, None),
                Arg("cudnn_off", bool, False)])
def _convolution(p, data, weight, bias=None):
    """Parity: src/operator/nn/convolution.cc (NCHW semantics).

    Lowering: one `lax.conv_general_dilated` → XLA conv → MXU.  The
    reference's im2col/cuDNN-autotune machinery is the compiler's job here.
    """
    k = p["kernel"]
    n = len(k)
    # __io_layout__ == "NHWC": GraphPlan's whole-graph layout pass says
    # the data input is ALREADY channels-last and the consumer wants a
    # channels-last output — no boundary transposes here (they exist
    # only at true graph edges).  Without it, the per-op global-flag
    # behavior stands (eager mx.nd.* calls).
    pre_cl = p.get("__io_layout__") == "NHWC"
    cl = pre_cl or (_layout.channels_last() and data.ndim == n + 2)
    if cl:
        # NCHW semantics, channels-last compute: boundary transposes
        # cancel pairwise across conv→BN→relu→conv chains (layout.py)
        if not pre_cl:
            data = _layout.to_cl(data)
        weight = _w_to_cl(weight, n)
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape, _conv_dims_cl(k) if cl else _conv_dims(k))
    pad = _tup(p["pad"], n, 0)
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=_tup(p["stride"], n),
        padding=[(q, q) for q in pad],
        rhs_dilation=_tup(p["dilate"], n),
        dimension_numbers=dn,
        feature_group_count=p["num_group"],
        # no preferred_element_type upcast: the MXU accumulates bf16
        # operands in f32 natively, and requesting f32 output breaks the
        # conv transpose rule (f32 cotangent x bf16 weight).
    )
    if not p["no_bias"]:
        out = out + (bias if cl else bias.reshape((1, -1) + (1,) * n))
    return out if pre_cl else (_layout.from_cl(out) if cl else out)


@register("Deconvolution", input_names=("data", "weight", "bias"),
          args=[Arg("kernel", "shape", required=True), Arg("stride", "shape", ()),
                Arg("dilate", "shape", ()), Arg("pad", "shape", ()),
                Arg("adj", "shape", ()), Arg("target_shape", "shape", ()),
                Arg("num_filter", int, required=True), Arg("num_group", int, 1),
                Arg("no_bias", bool, True), Arg("layout", str, None),
                Arg("workspace", int, 512), Arg("cudnn_tune", str, None),
                Arg("cudnn_off", bool, False)])
def _deconvolution(p, data, weight, bias=None):
    """Parity: src/operator/nn/deconvolution.cc — transposed convolution."""
    k = p["kernel"]
    n = len(k)
    stride = _tup(p["stride"], n)
    pad = _tup(p["pad"], n, 0)
    dilate = _tup(p["dilate"], n)
    adj = _tup(p["adj"], n, 0)
    # gradient-of-conv formulation: lhs_dilation=stride, padding k-1-p
    eff_k = tuple((k[i] - 1) * dilate[i] + 1 for i in range(n))
    # weight layout for Deconvolution is (in_ch, out_ch/group, *k) → flip+swap
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    if p["num_group"] > 1:
        w = w.reshape((p["num_group"], -1) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((-1,) + w.shape[2:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    pre_cl = p.get("__io_layout__") == "NHWC"
    cl = pre_cl or (_layout.channels_last() and data.ndim == n + 2)
    if cl:
        if not pre_cl:
            data = _layout.to_cl(data)
        w = _w_to_cl(w, n)
    dn = lax.conv_dimension_numbers(
        data.shape, w.shape, _conv_dims_cl(k) if cl else _conv_dims(k))
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * n,
        padding=[(eff_k[i] - 1 - pad[i], eff_k[i] - 1 - pad[i] + adj[i]) for i in range(n)],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=p["num_group"])
    if not p["no_bias"] and bias is not None:
        out = out + (bias if cl else bias.reshape((1, -1) + (1,) * n))
    return out if pre_cl else (_layout.from_cl(out) if cl else out)


# ---------------------------------------------------------------------------
# Pooling (parity: src/operator/nn/pooling.cc + legacy pooling_v1)
# ---------------------------------------------------------------------------
@register("Pooling", input_names=("data",), aliases=("Pooling_v1",),
          args=[Arg("kernel", "shape", ()), Arg("pool_type", str, "max"),
                Arg("global_pool", bool, False), Arg("stride", "shape", ()),
                Arg("pad", "shape", ()), Arg("pooling_convention", str, "valid"),
                Arg("cudnn_off", bool, False)])
def _pooling(p, x):
    n = x.ndim - 2
    pre_cl = p.get("__io_layout__") == "NHWC"
    if p["global_pool"]:
        axes = (tuple(range(1, x.ndim - 1)) if pre_cl
                else tuple(range(2, x.ndim)))
        red = jnp.max if p["pool_type"] == "max" else jnp.mean
        if p["pool_type"] == "sum":
            red = jnp.sum
        return red(x, axis=axes, keepdims=True)
    cl = pre_cl or (_layout.channels_last() and x.ndim >= 3)
    if cl and not pre_cl:
        x = _layout.to_cl(x)
    sp = 1 if cl else 2  # first spatial axis
    k = _tup(p["kernel"], n)
    stride = _tup(p["stride"], n)
    pad = _tup(p["pad"], n, 0)
    lo_hi = []
    for i in range(n):
        lo, hi = pad[i], pad[i]
        if p["pooling_convention"] == "full":
            # ceil output size: add extra high padding
            size = x.shape[sp + i] + 2 * pad[i] - k[i]
            extra = (-size) % stride[i]
            hi += extra
        lo_hi.append((lo, hi))
    if cl:
        window = (1,) + k + (1,)
        strides = (1,) + stride + (1,)
        padding = ((0, 0),) + tuple(lo_hi) + ((0, 0),)
    else:
        window = (1, 1) + k
        strides = (1, 1) + stride
        padding = ((0, 0), (0, 0)) + tuple(lo_hi)
    out = _pool_impl(p, x, n, sp, k, stride, lo_hi, window, strides,
                     padding, cl)
    return out if pre_cl else (_layout.from_cl(out) if cl else out)


def _pool_impl(p, x, n, sp, k, stride, lo_hi, window, strides, padding, cl):
    if p["pool_type"] == "max":
        # Patch-stack max instead of lax.reduce_window(max): the
        # select_and_gather_add gradient packs values into 64-bit pairs,
        # which the TPU backend rejects under jax_enable_x64; static
        # strided slices + reduce_max differentiate cleanly and XLA
        # fuses them.
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        vol = 1
        for ki in k:
            vol *= ki
        if vol > 64:
            # large kernels (SPP-style): patch-stack would emit vol slices
            # and a vol-times-output buffer; fall back to reduce_window
            # (grad unsupported on TPU+x64, but these never appear in
            # trained backbones)
            return lax.reduce_window(x, jnp.asarray(init, x.dtype), lax.max,
                                     window, strides, padding)
        xp = jnp.pad(x, padding, constant_values=jnp.asarray(init, x.dtype))
        out_sz = [(xp.shape[sp + i] - k[i]) // stride[i] + 1
                  for i in range(n)]
        parts = []
        for offs in _itertools.product(*[range(ki) for ki in k]):
            spatial = tuple(
                slice(offs[i], offs[i] + (out_sz[i] - 1) * stride[i] + 1,
                      stride[i]) for i in range(n))
            idx = (slice(None),) + spatial + (slice(None),) if cl \
                else (slice(None), slice(None)) + spatial
            parts.append(xp[idx])
        return jnp.max(jnp.stack(parts), axis=0)
    denom = 1
    for d in k:
        denom *= d
    if jnp.issubdtype(x.dtype, jnp.floating):
        # sum/avg pooling as a grouped conv with a uniform kernel: lands
        # on the MXU and differentiates cleanly — jax 0.9 cannot
        # linearize reduce_window_sum under jit ('Linearization failed
        # to produce known values'), so the reduce_window form would
        # break any training graph containing windowed avg pooling
        C = x.shape[-1] if cl else x.shape[1]
        w = jnp.ones((k + (1, C)) if cl else ((C, 1) + k), x.dtype)
        if p["pool_type"] != "sum":
            # reference 'valid' convention divides by the full kernel
            # size, padding included
            w = w / denom
        dn = lax.conv_dimension_numbers(
            x.shape, w.shape, _conv_dims_cl(k) if cl else _conv_dims(k))
        return lax.conv_general_dilated(
            x, w, window_strides=stride, padding=lo_hi,
            dimension_numbers=dn, feature_group_count=C)
    summed = lax.reduce_window(x, jnp.asarray(0, x.dtype), lax.add,
                               window, strides, padding)
    if p["pool_type"] == "sum":
        return summed
    # avg: reference divides by full kernel size (padding included)
    return summed / denom


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------
@register("BatchNorm", input_names=("data", "gamma", "beta", "moving_mean", "moving_var"),
          args=[Arg("eps", float, 1e-3), Arg("momentum", float, 0.9),
                Arg("fix_gamma", bool, True), Arg("use_global_stats", bool, False),
                Arg("output_mean_var", bool, False), Arg("axis", int, 1),
                Arg("cudnn_off", bool, False)],
          num_outputs=3, aux_inputs=[3, 4], takes_is_train=True,
          f32_inputs=(1, 2, 3, 4), aliases=("BatchNorm_v1",))
def _batch_norm(p, x, gamma, beta, mov_mean, mov_var):
    """Parity: src/operator/nn/batch_norm.cc.

    Outputs (out, saved_mean, saved_var) + updated aux (moving_mean,
    moving_var) which the runtime writes back into the aux NDArrays.
    """
    ax = p["axis"] % x.ndim
    pre_cl = p.get("__io_layout__") == "NHWC"  # logical axis 1, already CL
    cl = pre_cl or (_layout.channels_last() and ax == 1 and x.ndim >= 3)
    if cl:
        # channels-last compute: the normalize chain stays in the same
        # layout as the surrounding convs (boundary transposes cancel)
        if not pre_cl:
            x = _layout.to_cl(x)
        ax = x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != ax)
    bshape = tuple(x.shape[ax] if i == ax else 1 for i in range(x.ndim))
    train = bool(p.get("__is_train__")) and not p["use_global_stats"]
    g = jnp.ones_like(gamma) if p["fix_gamma"] else gamma
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=red)
        var = jnp.var(xf, axis=red)
        m = p["momentum"]
        new_mm = mov_mean * m + mean.astype(mov_mean.dtype) * (1 - m)
        new_mv = mov_var * m + var.astype(mov_var.dtype) * (1 - m)
    else:
        mean, var = mov_mean, mov_var
        new_mm, new_mv = mov_mean, mov_var
    inv_std = lax.rsqrt(var + p["eps"])
    # scale/shift cast to the activation dtype so bf16 stays bf16 end to
    # end (gamma/beta/moving stats themselves are f32, reference fp16 BN)
    out = (x - mean.reshape(bshape).astype(x.dtype)) * (
        inv_std.reshape(bshape).astype(x.dtype)) * \
        g.reshape(bshape).astype(x.dtype) + \
        beta.reshape(bshape).astype(x.dtype)
    if cl and not pre_cl:
        out = _layout.from_cl(out)
    return (out, mean.astype(x.dtype), var.astype(x.dtype),
            lax.stop_gradient(new_mm), lax.stop_gradient(new_mv))


@register("LayerNorm", input_names=("data", "gamma", "beta"),
          args=[Arg("axis", int, -1), Arg("eps", float, 1e-5),
                Arg("output_mean_var", bool, False)],
          num_outputs=3)
def _layer_norm(p, x, gamma, beta):
    ax = p["axis"] % x.ndim
    mean = jnp.mean(x, axis=ax, keepdims=True)
    var = jnp.var(x, axis=ax, keepdims=True)
    inv = lax.rsqrt(var + p["eps"])
    bshape = tuple(x.shape[ax] if i == ax else 1 for i in range(x.ndim))
    out = (x - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)


@register("InstanceNorm", input_names=("data", "gamma", "beta"),
          args=[Arg("eps", float, 1e-3)])
def _instance_norm(p, x, gamma, beta):
    """Parity: src/operator/instance_norm.cc — normalize over spatial dims."""
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + p["eps"]) * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization", input_names=("data",),
          args=[Arg("eps", float, 1e-10), Arg("mode", str, "instance")])
def _l2_normalization(p, x):
    """Parity: src/operator/l2_normalization.cc."""
    if p["mode"] == "instance":
        red = tuple(range(1, x.ndim))
        kd = True
    elif p["mode"] == "channel":
        red = (1,)
        kd = True
    else:  # spatial
        red = tuple(range(2, x.ndim))
        kd = True
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=kd) + p["eps"])
    return x / norm


@register("LRN", input_names=("data",),
          args=[Arg("alpha", float, 1e-4), Arg("beta", float, 0.75),
                Arg("knorm", float, 2.0), Arg("nsize", int, required=True)])
def _lrn(p, x):
    """Parity: src/operator/lrn.cc — cross-channel local response norm.

    The window sum is nsize shifted channel slices added together (not
    lax.reduce_window: its sum flavor fails to LINEARIZE inside jit on
    this jax — 'Linearization failed to produce known values' — found
    by the finite-difference tier; slices also fuse better on TPU for
    the tiny windows LRN uses)."""
    if p["nsize"] % 2 == 0:
        raise MXNetError(
            f"LRN nsize must be odd (got {p['nsize']}): the window is "
            "centered on each channel")
    half = p["nsize"] // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (x.ndim - 2))
    C = x.shape[1]
    ssum = padded[:, 0:C]
    for i in range(1, p["nsize"]):
        ssum = ssum + padded[:, i:i + C]
    return x / jnp.power(p["knorm"] + p["alpha"] / p["nsize"] * ssum, p["beta"])


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------
@register("Activation", input_names=("data",),
          args=[Arg("act_type", str, required=True)])
def _activation(p, x):
    t = p["act_type"]
    if t == "relu":
        return jnp.maximum(x, 0)
    if t == "sigmoid":
        return jax.nn.sigmoid(x)
    if t == "tanh":
        return jnp.tanh(x)
    if t == "softrelu":
        return jnp.logaddexp(x, 0.0)
    if t == "softsign":
        return x / (1 + jnp.abs(x))
    raise MXNetError(f"unknown act_type {t}")


@register("LeakyReLU", input_names=("args",), variadic=True,
          args=[Arg("act_type", str, "leaky"), Arg("slope", float, 0.25),
                Arg("lower_bound", float, 0.125), Arg("upper_bound", float, 0.334)])
def _leaky_relu(p, x, gamma=None):
    """Parity: src/operator/leaky_relu.cc (leaky/elu/prelu/selu; rrelu uses
    the midpoint slope deterministically, matching reference test mode)."""
    t = p["act_type"]
    if t == "leaky":
        return jnp.where(x > 0, x, p["slope"] * x)
    if t == "elu":
        return jnp.where(x > 0, x, p["slope"] * jnp.expm1(x))
    if t == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if gamma.ndim == 1 and x.ndim > 1 else gamma
        return jnp.where(x > 0, x, g * x)
    if t == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
    if t == "rrelu":
        slope = (p["lower_bound"] + p["upper_bound"]) / 2.0
        return jnp.where(x > 0, x, slope * x)
    raise MXNetError(f"unknown act_type {t}")


# ---------------------------------------------------------------------------
# Softmax family
# ---------------------------------------------------------------------------
@register("softmax", input_names=("data",),
          args=[Arg("axis", int, -1), Arg("temperature", float, None)])
def _softmax(p, x):
    t = p.get("temperature") or 1.0
    return jax.nn.softmax(x / t, axis=p["axis"])


@register("log_softmax", input_names=("data",),
          args=[Arg("axis", int, -1), Arg("temperature", float, None)])
def _log_softmax(p, x):
    t = p.get("temperature") or 1.0
    return jax.nn.log_softmax(x / t, axis=p["axis"])


@register("SoftmaxActivation", input_names=("data",),
          args=[Arg("mode", str, "instance")])
def _softmax_activation(p, x):
    if p["mode"] == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register("softmax_cross_entropy", input_names=("data", "label"))
def _softmax_cross_entropy(p, data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


# --- loss-output ops with MXNet's folded-gradient semantics ----------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _softmax_output_core(pt, data, label):
    p = dict(pt)
    ax = 1 if p["multi_output"] else -1
    if p["preserve_shape"] or p["multi_output"]:
        return jax.nn.softmax(data, axis=ax)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_fwd(pt, data, label):
    out = _softmax_output_core(pt, data, label)
    return out, (out, label)


def _softmax_output_bwd(pt, res, g):
    p = dict(pt)
    out, label = res
    ax = 1 if p["multi_output"] else out.ndim - 1
    nclass = out.shape[ax]
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, nclass, axis=ax, dtype=out.dtype)
    grad = out - onehot
    valid = jnp.ones_like(lab, dtype=out.dtype)
    if p["use_ignore"]:
        keep = (lab != int(p["ignore_label"])).astype(out.dtype)
        grad = grad * jnp.expand_dims(keep, ax)
        valid = keep
    scale = p["grad_scale"]
    if p["normalization"] == "batch":
        scale = scale / out.shape[0]
    elif p["normalization"] == "valid":
        scale = scale / jnp.maximum(jnp.sum(valid), 1.0)
    grad = grad * scale
    if p["out_grad"]:
        grad = grad * g
    return grad.astype(out.dtype), jnp.zeros_like(label)


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", input_names=("data", "label"), aliases=("Softmax",),
          f32_inputs=(1,),
          args=[Arg("grad_scale", float, 1.0), Arg("ignore_label", float, -1.0),
                Arg("multi_output", bool, False), Arg("use_ignore", bool, False),
                Arg("preserve_shape", bool, False), Arg("normalization", str, "null"),
                Arg("out_grad", bool, False), Arg("smooth_alpha", float, 0.0)])
def _softmax_output(p, data, label):
    """Parity: src/operator/softmax_output-inl.h — forward softmax, backward
    (p − onehot(label))·grad_scale with ignore/normalization handling."""
    return _softmax_output_core(tuple(sorted(p.items())), data, label)


def _make_regression(name, fwd, bwd):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
    def core(scale, data, label):
        return fwd(data)

    def f(scale, data, label):
        out = fwd(data)
        return out, (out, label)

    def b(scale, res, g):
        out, label = res
        num_output = 1
        for d in label.shape[1:]:
            num_output *= d
        grad = bwd(out, label.reshape(out.shape)) * (scale / num_output)
        return grad.astype(out.dtype), jnp.zeros_like(label)

    core.defvjp(f, b)

    @register(name, input_names=("data", "label"),
              args=[Arg("grad_scale", float, 1.0)])
    def op(p, data, label):
        """Parity: src/operator/regression_output-inl.h:75-97 — gradient is
        grad_scale/num_output · BackwardOp(out, label)."""
        return core(p["grad_scale"], data, label)
    return op


_make_regression("LinearRegressionOutput", lambda x: x, lambda o, l: o - l)
_make_regression("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l)
_make_regression("MAERegressionOutput", lambda x: x, lambda o, l: jnp.sign(o - l))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _make_loss_core(pt, data):
    return data


def _make_loss_fwd(pt, data):
    # 0-size carrier keeps shape AND dtype in the residual (a bare
    # np.dtype is not a jax type)
    return data, (data.shape, jnp.zeros((0,), data.dtype))


def _make_loss_bwd(pt, res, g):
    shape, carrier = res
    p = dict(pt)
    scale = p["grad_scale"]
    if p["normalization"] == "batch":
        scale = scale / shape[0]
    # explicit dtype: a bare python float would make jnp.full emit f64
    # under jax_enable_x64, poisoning every upstream vjp with dtype
    # mismatches (lax.div f64 vs f32)
    return (jnp.full(shape, scale, carrier.dtype),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss", input_names=("data",),
          args=[Arg("grad_scale", float, 1.0), Arg("valid_thresh", float, 0.0),
                Arg("normalization", str, "null")])
def _make_loss_legacy(p, data):
    """Parity: src/operator/make_loss.cc — identity fwd, constant grad."""
    return _make_loss_core(tuple(sorted(p.items())), data)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _svm_output_op(pt, data, label):
    return data


def _svm_output_op_fwd(pt, data, label):
    return data, (data, label)


def _svm_output_op_bwd(pt, res, g):
    """Parity: src/operator/svm_output.cc L1_SVM/L2_SVM kernels —
    one-vs-all hinge gradient, incoming head gradient folded away
    (loss-output semantics like SoftmaxOutput)."""
    p = dict(pt)
    out, label = res
    flat = out.reshape(out.shape[0], -1)
    m = p["margin"]
    reg = p["regularization_coefficient"]
    onehot = jax.nn.one_hot(label.astype(jnp.int32).reshape(-1),
                            flat.shape[1], dtype=flat.dtype)
    if p["use_linear"]:  # L1-SVM
        g_true = -(m > flat).astype(flat.dtype) * reg
        g_other = (m > -flat).astype(flat.dtype) * reg
    else:  # L2-SVM (default)
        g_true = jnp.where(m > flat, -2.0 * reg * (m - flat),
                           jnp.zeros((), flat.dtype))
        g_other = jnp.where(m > -flat, 2.0 * reg * (m + flat),
                            jnp.zeros((), flat.dtype))
    grad = onehot * g_true + (1 - onehot) * g_other
    return grad.reshape(out.shape).astype(out.dtype), jnp.zeros_like(label)


_svm_output_op.defvjp(_svm_output_op_fwd, _svm_output_op_bwd)


@register("SVMOutput", input_names=("data", "label"),
          args=[Arg("margin", float, 1.0), Arg("regularization_coefficient", float, 1.0),
                Arg("use_linear", bool, False)])
def _svm_output(p, data, label):
    """Parity: src/operator/svm_output.cc — identity forward, one-vs-all
    hinge backward (L2-SVM default, L1 via use_linear)."""
    return _svm_output_op(tuple(sorted(p.items())), data, label)


# ---------------------------------------------------------------------------
# Dropout (needs RNG + is_train)
# ---------------------------------------------------------------------------
@register("Dropout", input_names=("data",),
          args=[Arg("p", float, 0.5), Arg("mode", str, "training"),
                Arg("axes", "shape", ())],
          needs_rng=True, takes_is_train=True)
def _dropout(p, x, key):
    """Parity: src/operator/nn/dropout.cc — inverted dropout."""
    rate = p["p"]
    train = bool(p.get("__is_train__")) or p["mode"] == "always"
    if not train or rate <= 0.0:
        return x
    shape = x.shape
    if p["axes"]:
        shape = tuple(1 if i in p["axes"] else s for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - rate, shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros((), x.dtype)).astype(x.dtype)


# ---------------------------------------------------------------------------
# UpSampling / misc vision
# ---------------------------------------------------------------------------
@register("UpSampling", input_names=("args",), variadic=True,
          args=[Arg("scale", int, required=True), Arg("sample_type", str, "nearest"),
                Arg("num_args", int, 1), Arg("workspace", int, 512),
                Arg("multi_input_mode", str, "concat"), Arg("num_filter", int, 0)])
def _upsampling(p, *xs):
    """Parity: src/operator/upsampling.cc (nearest; bilinear via resize)."""
    s = p["scale"]
    outs = []
    for x in xs:
        if p["sample_type"] == "nearest":
            out = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
        else:
            out = jax.image.resize(x, x.shape[:2] + (x.shape[2] * s, x.shape[3] * s),
                                   method="bilinear")
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=1)
