"""Compatibility closure over the reference's remaining op names.

Audited against every NNVM_REGISTER_OP / MXNET_REGISTER_OP_PROPERTY in
/root/reference/src/operator (round-2 op-gap sweep).  Three tiers:

1. alias-to-equivalent: `_`-prefixed elementwise tensor-tensor ops are the
   reference's operator-sugar kernels for same-shape operands; the
   broadcast_* registrations are behavior-compatible supersets, so these
   are pure aliases.  Likewise `_linalg_*` → `linalg_*` (the reference
   registers both spellings, src/operator/tensor/la_op.cc:73).
2. implemented here: reshape_like, _slice_assign(_scalar) (setitem
   kernels, matrix_op.cc:313), _identity_with_attr_like_rhs (graph-pass
   helper), _linalg_gelqf / _linalg_syevd (la_op.cc LQ and
   symmetric-eig factorizations), IdentityAttachKLSparseReg
   (identity_attach_KL_sparse_reg.cc — KL sparsity penalty on
   activations, with the reference's moving-average aux state).
3. intentionally absent (no TPU meaning, documented in PARITY.md):
   _CrossDeviceCopy (engine-internal), _NDArray/_Native (old C plugin
   bridge — the torch bridge is the supported path), _broadcast_backward
   (grad-pass internal), CuDNNBatchNorm (aliased to BatchNorm).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import Arg
from .registry import OP_ALIASES, register

# -- tier 1: aliases --------------------------------------------------------
_ALIAS_MAP = {
    "_equal": "broadcast_equal",
    "_not_equal": "broadcast_not_equal",
    "_greater": "broadcast_greater",
    "_greater_equal": "broadcast_greater_equal",
    "_lesser": "broadcast_lesser",
    "_lesser_equal": "broadcast_lesser_equal",
    "_maximum": "broadcast_maximum",
    "_minimum": "broadcast_minimum",
    "_mod": "broadcast_mod",
    "_power": "broadcast_power",
    "_hypot": "broadcast_hypot",
    "_grad_add": "elemwise_add",
    "_linalg_gemm": "linalg_gemm",
    "_linalg_gemm2": "linalg_gemm2",
    "_linalg_potrf": "linalg_potrf",
    "_linalg_potri": "linalg_potri",
    "_linalg_trmm": "linalg_trmm",
    "_linalg_trsm": "linalg_trsm",
    "_linalg_sumlogdiag": "linalg_sumlogdiag",
    "_linalg_syrk": "linalg_syrk",
    "_sparse_retain": "sparse_retain",
    "_contrib_CTCLoss": "_contrib_ctc_loss",
    "_contrib_SparseEmbedding": "Embedding",
    "CuDNNBatchNorm": "BatchNorm",
    # dense forms of the row-sparse-preserving scatter kernels
    # (elemwise_binary_scalar_op.cc _scatter_* — storage preservation is
    # an NDArray-level concern here)
    "_scatter_plus_scalar": "_plus_scalar",
    "_scatter_minus_scalar": "_minus_scalar",
    "_scatter_elemwise_div": "elemwise_div",
}
for _alias, _target in _ALIAS_MAP.items():
    OP_ALIASES.setdefault(_alias, OP_ALIASES.get(_target, _target))


# -- tier 2: implementations ------------------------------------------------
@register("reshape_like", input_names=("lhs", "rhs"))
def _reshape_like(p, lhs, rhs):
    """Parity: matrix_op.cc reshape_like — lhs reshaped to rhs's shape
    (gradient flows to lhs only)."""
    return lhs.reshape(rhs.shape)


@register("_identity_with_attr_like_rhs", input_names=("lhs", "rhs"))
def _identity_with_attr_like_rhs(p, lhs, rhs):
    """Parity: elemwise_unary_op_basic.cc — identity on lhs; rhs only
    donates graph attrs (storage type there, sharding here; its grad is
    dense zeros via zero_like_grad)."""
    return lhs


def _slice_tuple(p, shape):
    begin = p["begin"]
    end = p["end"]
    step = p.get("step") or ()
    out = []
    for i in range(len(shape)):
        b = begin[i] if i < len(begin) and begin[i] is not None else None
        e = end[i] if i < len(end) and end[i] is not None else None
        s = step[i] if i < len(step) and step[i] is not None else None
        out.append(slice(b, e, s))
    return tuple(out)


@register("_slice_assign", input_names=("lhs", "rhs"),
          aliases=("_crop_assign",),
          args=[Arg("begin", "shape", required=True),
                Arg("end", "shape", required=True),
                Arg("step", "shape", ())])
def _slice_assign(p, lhs, rhs):
    """Parity: matrix_op.cc:313 — functional setitem: lhs with the cropped
    region replaced by rhs."""
    return lhs.at[_slice_tuple(p, lhs.shape)].set(rhs.astype(lhs.dtype))


@register("_slice_assign_scalar", input_names=("lhs",),
          aliases=("_crop_assign_scalar",),
          args=[Arg("scalar", float, 0.0),
                Arg("begin", "shape", required=True),
                Arg("end", "shape", required=True),
                Arg("step", "shape", ())])
def _slice_assign_scalar(p, lhs):
    return lhs.at[_slice_tuple(p, lhs.shape)].set(
        jnp.asarray(p["scalar"], lhs.dtype))


@register("_linalg_gelqf", input_names=("A",), aliases=("linalg_gelqf",),
          num_outputs=2)
def _linalg_gelqf(p, a):
    """Parity: la_op.cc gelqf — LQ factorization A = L @ Q with Q's rows
    orthonormal.  Via QR of Aᵀ: Aᵀ = Q₁R₁ → A = R₁ᵀ Q₁ᵀ."""
    q1, r1 = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r1, -1, -2), jnp.swapaxes(q1, -1, -2)


@register("_linalg_syevd", input_names=("A",), aliases=("linalg_syevd",),
          num_outputs=2)
def _linalg_syevd(p, a):
    """Parity: la_op.cc syevd — symmetric eigendecomposition
    A = Uᵀ diag(L) U (U rows are eigenvectors)."""
    lam, u = jnp.linalg.eigh(a)
    return jnp.swapaxes(u, -1, -2), lam


@register("IdentityAttachKLSparseReg", input_names=("data", "moving_avg"),
          aux_inputs=[1],
          args=[Arg("sparseness_target", float, 0.1),
                Arg("penalty", float, 0.001),
                Arg("momentum", float, 0.9)])
def _identity_attach_kl_sparse_reg(p, x, moving_avg=None):
    """Parity: identity_attach_KL_sparse_reg-inl.h — identity forward
    whose backward adds the KL-divergence sparsity-penalty gradient,
    penalty · (-ρ/ρ̂ + (1-ρ)/(1-ρ̂)) per element, where ρ̂ is the
    momentum moving average of the per-unit batch-mean activation
    (the reference's moving_avg aux state, :103-111)."""
    rho = p["sparseness_target"]
    pen = p["penalty"]
    mom = p["momentum"]
    batch_mean = jnp.mean(x, axis=0)
    if moving_avg is None:
        new_avg = batch_mean
    else:
        new_avg = mom * moving_avg + (1 - mom) * batch_mean

    @jax.custom_vjp
    def ident(v, avg):
        return v

    def fwd(v, avg):
        return v, avg

    def bwd(avg, g):
        rho_hat = jnp.clip(avg, 1e-6, 1 - 1e-6)[None, :]
        extra = pen * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + jnp.broadcast_to(extra, g.shape).astype(g.dtype),
                jnp.zeros_like(avg))

    ident.defvjp(fwd, bwd)
    return ident(x, new_avg), new_avg
