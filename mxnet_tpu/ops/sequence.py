"""Sequence operators + fused RNN as lax.scan.

Reference parity: `src/operator/sequence_{last,mask,reverse}.cc` and the
fused `RNN` op (`src/operator/rnn.cc` / `cudnn_rnn-inl.h`).  The reference's
RNN is GPU-only (`src/operator/rnn.cc:32-33` fatals on CPU); here it is a
`lax.scan` over time — XLA compiles the whole unrolled recurrence, runs on
TPU/CPU alike, and the packed-parameter layout matches cuDNN's so
`mx.rnn`/`gluon.rnn` weight pack/unpack round-trips.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import Arg, MXNetError
from .registry import register


@register("SequenceLast", input_names=("data", "sequence_length"), variadic=True,
          args=[Arg("use_sequence_length", bool, False), Arg("axis", int, 0)])
def _sequence_last(p, data, seq_len=None):
    ax = p["axis"]
    if not p["use_sequence_length"] or seq_len is None:
        return jnp.take(data, data.shape[ax] - 1, axis=ax)
    idx = jnp.maximum(seq_len.astype(jnp.int32) - 1, 0)  # (batch,)
    moved = jnp.moveaxis(data, ax, 0)  # (seq, batch, ...)
    return jnp.take_along_axis(
        moved, idx.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0)[0]


@register("SequenceMask", input_names=("data", "sequence_length"), variadic=True,
          args=[Arg("use_sequence_length", bool, False), Arg("value", float, 0.0),
                Arg("axis", int, 0)])
def _sequence_mask(p, data, seq_len=None):
    if not p["use_sequence_length"] or seq_len is None:
        return data
    ax = p["axis"]
    steps = jnp.arange(data.shape[ax])
    # data layout: (seq, batch, ...) for axis=0 or (batch, seq, ...) for axis=1
    if ax == 0:
        mask = steps[:, None] < seq_len[None, :]
    else:
        mask = steps[None, :] < seq_len[:, None]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(p["value"], data.dtype))


@register("SequenceReverse", input_names=("data", "sequence_length"), variadic=True,
          args=[Arg("use_sequence_length", bool, False), Arg("axis", int, 0)])
def _sequence_reverse(p, data, seq_len=None):
    if not p["use_sequence_length"] or seq_len is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    L = seq_len.astype(jnp.int32)[None, :]
    idx = jnp.where(steps < L, L - 1 - steps, steps)  # (seq, batch)
    return jnp.take_along_axis(
        data, idx.reshape(idx.shape + (1,) * (data.ndim - 2)), axis=0)


# ---------------------------------------------------------------------------
# Fused RNN (cuDNN-compatible packed parameters)
# ---------------------------------------------------------------------------
_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total packed parameter count (matches cuDNN layout used by the
    reference's cudnn_rnn-inl.h and python/mxnet/rnn/rnn_cell.py unfuse)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        size += d * g * state_size * (in_sz + state_size)  # W + R
    size += num_layers * d * g * state_size * 2  # biases bW + bR
    return size


def _unpack_rnn_params(params, num_layers, input_size, state_size, bidir, mode):
    g = _GATES[mode]
    d = 2 if bidir else 1
    ws, rs, bws, brs = [], [], [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        lw, lr = [], []
        for _ in range(d):
            n = g * state_size * in_sz
            lw.append(params[off:off + n].reshape(g * state_size, in_sz))
            off += n
            n = g * state_size * state_size
            lr.append(params[off:off + n].reshape(g * state_size, state_size))
            off += n
        ws.append(lw)
        rs.append(lr)
    for layer in range(num_layers):
        lbw, lbr = [], []
        for _ in range(d):
            n = g * state_size
            lbw.append(params[off:off + n])
            off += n
            lbr.append(params[off:off + n])
            off += n
        bws.append(lbw)
        brs.append(lbr)
    return ws, rs, bws, brs


def _cell_step(mode, state_size):
    if mode == "lstm":
        def step(carry, gates):
            h, c = carry
            i, f, gg, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            gg = jnp.tanh(gg)
            c2 = f * c + i * gg
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2
        return step
    if mode == "gru":
        def step(carry, pair):
            h = carry[0]
            wx, rh = pair  # (batch, 3H) each: [r, z, n] cuDNN order
            rx, zx, nx = jnp.split(wx, 3, axis=-1)
            rh_, zh_, nh_ = jnp.split(rh, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh_)
            z = jax.nn.sigmoid(zx + zh_)
            n = jnp.tanh(nx + r * nh_)
            h2 = (1 - z) * n + z * h
            return (h2,), h2
        return step
    act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

    def step(carry, gates):
        h2 = act(gates)
        return (h2,), h2
    return step


def _run_layer(x, h0, c0, W, R, bW, bR, mode, reverse):
    """One direction of one layer. x: (T, B, in). Returns (T,B,H), hT, cT."""
    T, B, _ = x.shape
    H = h0.shape[-1]
    # hoist the input projection out of the scan: one big MXU matmul
    wx = jnp.einsum("tbi,gi->tbg", x, W) + bW + bR
    step = _cell_step(mode, H)

    if mode == "lstm":
        def body(carry, wxt):
            h, c = carry
            gates = wxt + jnp.matmul(h, R.T)
            return step((h, c), gates)
        carry, out = lax.scan(body, (h0, c0), wx, reverse=reverse)
        return out, carry[0], carry[1]
    if mode == "gru":
        def body(carry, wxt):
            (h,) = carry
            rh = jnp.matmul(h, R.T)
            return step((h,), (wxt, rh))
        carry, out = lax.scan(body, (h0,), wx, reverse=reverse)
        return out, carry[0], None

    def body(carry, wxt):
        (h,) = carry
        gates = wxt + jnp.matmul(h, R.T)
        return step((h,), gates)
    carry, out = lax.scan(body, (h0,), wx, reverse=reverse)
    return out, carry[0], None


@register("RNN", input_names=("data", "parameters", "state", "state_cell"),
          variadic=True,
          args=[Arg("state_size", int, required=True), Arg("num_layers", int, required=True),
                Arg("bidirectional", bool, False), Arg("mode", str, required=True),
                Arg("p", float, 0.0), Arg("state_outputs", bool, False),
                Arg("lstm_state_clip_min", float, None),
                Arg("lstm_state_clip_max", float, None),
                Arg("use_default_state", bool, False)],
          num_outputs=3, takes_is_train=True, needs_rng=True)
def _rnn(p, data, parameters, *rest):
    """Fused multi-layer (bi)RNN/LSTM/GRU.

    data: (seq_len, batch, input_size); state: (L*D, batch, H).
    use_default_state=True builds zero initial states inside the op
    (shapes are concrete here), so symbol graphs / hybridized gluon RNN
    layers need no explicit state inputs.
    Outputs (out, state_out, statecell_out) — the executor exposes the first
    1 or 3 depending on state_outputs, mirroring the reference op.
    """
    key = rest[-1]                  # PRNG key (needs_rng appends last)
    rest = rest[:-1]
    state = rest[0] if len(rest) > 0 else None
    state_cell = rest[1] if len(rest) > 1 else None
    mode = p["mode"]
    if mode not in _GATES:
        raise MXNetError(f"unknown RNN mode {mode}")
    L, H = p["num_layers"], p["state_size"]
    bidir = p["bidirectional"]
    d = 2 if bidir else 1
    T, B, I = data.shape
    if state is None:
        # use_default_state marks graphs composed without state inputs;
        # an explicitly provided state always wins
        state = jnp.zeros((L * d, B, H), data.dtype)
    if mode == "lstm" and state_cell is None:
        state_cell = jnp.zeros((L * d, B, H), data.dtype)
    ws, rs, bws, brs = _unpack_rnn_params(parameters, L, I, H, bidir, mode)
    hs = state.reshape(L, d, B, H)
    cs = state_cell.reshape(L, d, B, H) if (mode == "lstm" and state_cell is not None) else None
    x = data
    h_out, c_out = [], []
    for layer in range(L):
        outs = []
        for direction in range(d):
            h0 = hs[layer, direction]
            c0 = cs[layer, direction] if cs is not None else None
            out, hT, cT = _run_layer(
                x, h0, c0, ws[layer][direction], rs[layer][direction],
                bws[layer][direction], brs[layer][direction], mode,
                reverse=(direction == 1))
            outs.append(out)
            h_out.append(hT)
            c_out.append(cT if cT is not None else hT)
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        # inter-layer dropout (parity: rnn-inl.h — applied to every
        # layer's output except the last, training mode only)
        if p["p"] > 0 and layer < L - 1 and bool(p.get("__is_train__")):
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - p["p"], x.shape)
            x = jnp.where(keep, x / (1.0 - p["p"]),
                          jnp.zeros((), x.dtype)).astype(x.dtype)
    state_out = jnp.stack(h_out).reshape(L * d, B, H)
    cell_out = jnp.stack(c_out).reshape(L * d, B, H)
    if mode == "lstm" and p.get("lstm_state_clip_min") is not None:
        cell_out = jnp.clip(cell_out, p["lstm_state_clip_min"], p["lstm_state_clip_max"])
    return x, state_out, cell_out
