"""Fused optimizer-update operators (parity: src/operator/optimizer_op.cc:39-287).

Each op mutates its weight (and state) inputs in place at the NDArray layer;
under jit the whole update fuses into one XLA kernel with donated buffers —
the TPU analog of the reference's fused CUDA update kernels.  `mp_*` variants
keep float32 master weights for low-precision training (the precedent for
bf16-on-TPU training).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import Arg
from .registry import register

_COMMON = [Arg("lr", float, required=True), Arg("wd", float, 0.0),
           Arg("rescale_grad", float, 1.0), Arg("clip_gradient", float, -1.0)]


def _prep_grad(p, grad, dtype=None):
    g = grad * p["rescale_grad"]
    if p["clip_gradient"] > 0:
        g = jnp.clip(g, -p["clip_gradient"], p["clip_gradient"])
    return g.astype(dtype) if dtype is not None else g


@register("sgd_update", input_names=("weight", "grad"), args=list(_COMMON),
          mutates_input=0, differentiable=False)
def _sgd_update(p, weight, grad):
    g = _prep_grad(p, grad, weight.dtype)
    return weight - p["lr"] * (g + p["wd"] * weight)


@register("sgd_mom_update", input_names=("weight", "grad", "mom"),
          args=_COMMON + [Arg("momentum", float, 0.0)],
          mutates_input=0, num_outputs=1, aux_inputs=[2], differentiable=False)
def _sgd_mom_update(p, weight, grad, mom):
    g = _prep_grad(p, grad, weight.dtype)
    new_mom = p["momentum"] * mom - p["lr"] * (g + p["wd"] * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", input_names=("weight", "grad", "weight32"),
          args=list(_COMMON), mutates_input=0, aux_inputs=[2], differentiable=False)
def _mp_sgd_update(p, weight, grad, weight32):
    """fp16/bf16 weights with fp32 master copy (parity: optimizer_op.cc:111)."""
    g = _prep_grad(p, grad.astype(jnp.float32))
    new_w32 = weight32 - p["lr"] * (g + p["wd"] * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", input_names=("weight", "grad", "mom", "weight32"),
          args=_COMMON + [Arg("momentum", float, 0.0)],
          mutates_input=0, aux_inputs=[2, 3], differentiable=False)
def _mp_sgd_mom_update(p, weight, grad, mom, weight32):
    g = _prep_grad(p, grad.astype(jnp.float32))
    new_mom = p["momentum"] * mom - p["lr"] * (g + p["wd"] * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", input_names=("weight", "grad", "mean", "var"),
          args=_COMMON + [Arg("beta1", float, 0.9), Arg("beta2", float, 0.999),
                          Arg("epsilon", float, 1e-8)],
          mutates_input=0, aux_inputs=[2, 3], differentiable=False)
def _adam_update(p, weight, grad, mean, var):
    g = _prep_grad(p, grad, weight.dtype) + p["wd"] * weight
    new_mean = p["beta1"] * mean + (1 - p["beta1"]) * g
    new_var = p["beta2"] * var + (1 - p["beta2"]) * jnp.square(g)
    out = weight - p["lr"] * new_mean / (jnp.sqrt(new_var) + p["epsilon"])
    return out, new_mean, new_var


@register("rmsprop_update", input_names=("weight", "grad", "n"),
          args=_COMMON + [Arg("gamma1", float, 0.95), Arg("epsilon", float, 1e-8),
                          Arg("clip_weights", float, -1.0)],
          mutates_input=0, aux_inputs=[2], differentiable=False)
def _rmsprop_update(p, weight, grad, n):
    g = _prep_grad(p, grad, weight.dtype) + p["wd"] * weight
    new_n = (1 - p["gamma1"]) * jnp.square(g) + p["gamma1"] * n
    out = weight - p["lr"] * g / jnp.sqrt(new_n + p["epsilon"])
    if p["clip_weights"] > 0:
        out = jnp.clip(out, -p["clip_weights"], p["clip_weights"])
    return out, new_n


@register("rmspropalex_update", input_names=("weight", "grad", "n", "g", "delta"),
          args=_COMMON + [Arg("gamma1", float, 0.95), Arg("gamma2", float, 0.9),
                          Arg("epsilon", float, 1e-8), Arg("clip_weights", float, -1.0)],
          mutates_input=0, aux_inputs=[2, 3, 4], differentiable=False)
def _rmspropalex_update(p, weight, grad, n, gbar, delta):
    g = _prep_grad(p, grad, weight.dtype) + p["wd"] * weight
    new_n = (1 - p["gamma1"]) * jnp.square(g) + p["gamma1"] * n
    new_g = (1 - p["gamma1"]) * g + p["gamma1"] * gbar
    new_delta = p["gamma2"] * delta - p["lr"] * g / jnp.sqrt(
        new_n - jnp.square(new_g) + p["epsilon"])
    out = weight + new_delta
    if p["clip_weights"] > 0:
        out = jnp.clip(out, -p["clip_weights"], p["clip_weights"])
    return out, new_n, new_g, new_delta


@register("ftrl_update", input_names=("weight", "grad", "z", "n"),
          args=_COMMON + [Arg("lamda1", float, 0.01), Arg("beta", float, 1.0)],
          mutates_input=0, aux_inputs=[2, 3], differentiable=False)
def _ftrl_update(p, weight, grad, z, n):
    g = _prep_grad(p, grad, weight.dtype)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / p["lr"]
    new_z = z + g - sigma * weight
    out = jnp.where(
        jnp.abs(new_z) <= p["lamda1"],
        jnp.zeros_like(weight),
        (jnp.sign(new_z) * p["lamda1"] - new_z) /
        ((p["beta"] + jnp.sqrt(new_n)) / p["lr"] + p["wd"]))
    return out, new_z, new_n
