"""Creation operators (parity: src/operator/tensor/init_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import Arg, np_dtype
from .registry import register

_CREATE_ARGS = [Arg("shape", "shape", ()), Arg("dtype", str, "float32"),
                Arg("ctx", str, None)]


@register("_zeros", input_names=(), args=list(_CREATE_ARGS), differentiable=False)
def _zeros(p):
    return jnp.zeros(p["shape"], np_dtype(p["dtype"]))


@register("_ones", input_names=(), args=list(_CREATE_ARGS), differentiable=False)
def _ones(p):
    return jnp.ones(p["shape"], np_dtype(p["dtype"]))


@register("_full", input_names=(),
          args=_CREATE_ARGS + [Arg("value", float, required=True)],
          differentiable=False)
def _full(p):
    return jnp.full(p["shape"], p["value"], np_dtype(p["dtype"]))


@register("_arange", input_names=(),
          args=[Arg("start", float, 0.0), Arg("stop", float, None),
                Arg("step", float, 1.0), Arg("repeat", int, 1),
                Arg("dtype", str, "float32"), Arg("ctx", str, None),
                Arg("infer_range", bool, False)],
          differentiable=False)
def _arange(p):
    out = jnp.arange(p["start"], p.get("stop"), p["step"], np_dtype(p["dtype"]))
    if p["repeat"] > 1:
        out = jnp.repeat(out, p["repeat"])
    return out


@register("_eye", input_names=(),
          args=[Arg("N", int, required=True), Arg("M", int, 0), Arg("k", int, 0),
                Arg("dtype", str, "float32"), Arg("ctx", str, None)],
          differentiable=False)
def _eye(p):
    m = p["M"] or p["N"]
    return jnp.eye(p["N"], m, k=p["k"], dtype=np_dtype(p["dtype"]))
