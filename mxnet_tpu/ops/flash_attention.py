"""Flash attention as a Pallas TPU kernel.

The hot-op showcase for the Pallas path (`/opt/skills/guides/pallas_guide.md`):
blocked online-softmax attention that never materializes the (T, T) score
matrix.  The grid is (batch*heads, q_blocks, k_blocks) with the k dimension
sequential: each program sees one (blk_q, D) query block and one (blk_k, D)
key/value block in VMEM, carrying running max/sum/accumulator scratch across
k steps — VMEM usage is O(blk·D), independent of sequence length.  Composes
with `parallel.sequence_parallel.ring_attention`, which rotates K/V shards
across chips while this kernel handles the on-chip block math.

Backward is a custom VJP that recomputes scores blockwise (lax.map over
q-blocks): peak extra memory O(blk_q · Tk) per (batch, head) — linear in
sequence length, the standard flash recompute trade.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..base import Arg
from .registry import register

NEG_INF = -1e30

# Mosaic availability probe result: None = not probed, True/False after.
# The axon tunnel compiles Pallas kernels via a REMOTE helper service
# that can be down while plain XLA works (observed: HTTP 500 from
# tpu_compile_helper during the r04c window) — in that state the flash
# path must degrade to the dense reference instead of failing the
# user's whole program at compile time.
_PALLAS_OK = None
_PALLAS_ERR = ""


# the probe compiles a MINIATURE OF THE REAL KERNEL (same scratch
# shapes, 3-D grid, dimension_semantics) in a SUBPROCESS with a
# timeout: the tunnel's failure modes are both a fast HTTP 500 from
# the remote Mosaic helper AND an indefinite hang (r2-r4 probes), and
# a trivial kernel succeeding would not prove the real one compiles.
_PROBE_SNIPPET = """
import sys
sys.path.insert(0, {repo!r})
from mxnet_tpu.ops import flash_attention as fa
import jax, jax.numpy as jnp
q = jnp.ones((1, 1, {blk}, 64), jnp.float32)
out = fa._flash_attention(q, q, q, 1.0, False, {blk}, {blk})
out.block_until_ready()
print("PALLAS_PROBE_OK")
"""


def pallas_available(timeout=150.0):
    """Probe (once per process) whether Pallas kernels actually compile
    on this backend.  Off-TPU the kernel runs in interpret mode (always
    works); on TPU a subprocess compiles a miniature of the real flash
    kernel through the actual Mosaic toolchain — a hang or error there
    marks Pallas unavailable without blocking the caller forever."""
    global _PALLAS_OK, _PALLAS_ERR
    if _PALLAS_OK is not None:
        return _PALLAS_OK
    import os
    if os.environ.get("MXT_PALLAS_PROBE"):
        # we ARE the probe subprocess: run the kernel for real
        _PALLAS_OK = True
        return True
    if jax.default_backend() != "tpu":
        _PALLAS_OK = True
        return True
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    snippet = _PROBE_SNIPPET.format(repo=repo, blk=128)
    # the child must NOT re-join the parent's jax.distributed cluster
    # as a duplicate rank — strip the launcher env contract
    child_env = {k: v for k, v in os.environ.items()
                 if not k.startswith("MXT_") and not k.startswith("DMLC_")}
    child_env["MXT_PALLAS_PROBE"] = "1"
    try:
        out = subprocess.run([_sys.executable, "-c", snippet],
                             capture_output=True, text=True,
                             timeout=timeout, env=child_env)
        log_path = os.environ.get("MXT_PALLAS_PROBE_LOG")
        if log_path:
            # VERBATIM toolchain output for the window artifact (the
            # r4 consistency record only kept a 300-char tail — not
            # enough to attribute the remote Mosaic 500 to infra);
            # atomic so a killed probe can't leave a torn artifact
            from ..base import atomic_write
            atomic_write(log_path,
                         "rc=%s\n--- stdout ---\n%s\n--- stderr ---\n%s"
                         % (out.returncode, out.stdout, out.stderr))
        if out.returncode == 0 and "PALLAS_PROBE_OK" in out.stdout:
            _PALLAS_OK = True
            return True
        tail = (out.stdout + out.stderr)[-1200:]
        low = tail.lower()
        if ("already in use" in low or "libtpu" in low and "lock" in low
                or "resource busy" in low):
            # INCONCLUSIVE: the parent holds the chip exclusively (a
            # normal TPU VM, not the shared tunnel).  Don't disable
            # flash because probing was impossible — behave as before
            # the probe existed
            _PALLAS_OK = True
            return True
        _PALLAS_ERR = tail[-1000:]
    except subprocess.TimeoutExpired:
        _PALLAS_ERR = "probe timed out after %.0fs (hung toolchain)" \
            % timeout
    except Exception as e:
        _PALLAS_ERR = "%s: %s" % (type(e).__name__, str(e)[:200])
    _PALLAS_OK = False
    import logging
    logging.warning(
        "Pallas kernel compilation unavailable on this backend (%s); "
        "flash attention falls back to the dense reference", _PALLAS_ERR)
    return False


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
               scale, causal, blk_q, blk_k):
    """Grid (BH, nq, nk); nk is sequential — scratch carries the online
    softmax state across k steps."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[...].astype(jnp.float32) * scale           # (blk_q, D)
    k = k_ref[...].astype(jnp.float32)                   # (blk_k, D)
    v = v_ref[...].astype(jnp.float32)
    s = q @ k.T                                          # (blk_q, blk_k)
    if causal:
        q_pos = qi * blk_q + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0)
        k_pos = ki * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_ref[:, 0]                                 # (blk_q,)
    l_prev = l_ref[:, 0]
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_blk)
    p = jnp.exp(s - m_new[:, None])
    corr = jnp.exp(m_prev - m_new)
    acc_ref[...] = acc_ref[...] * corr[:, None] + p @ v
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] /
                      jnp.maximum(l_ref[:, 0], 1e-30)[:, None]
                      ).astype(o_ref.dtype)


def _dense_reference(q, k, v, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, scale, causal, blk_q, blk_k):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    # MXT_FLASH_INTERPRET=1 forces the interpret lowering (pure XLA, no
    # Mosaic) even on TPU — the kernel stays validatable at real shapes
    # when the tunnel's remote Mosaic helper is down (VERDICT r4 #5)
    import os as _os
    interp = (jax.default_backend() != "tpu"
              or bool(_os.environ.get("MXT_FLASH_INTERPRET")))
    if Tq % blk_q or Tk % blk_k or (not interp and not pallas_available()):
        return _dense_reference(q, k, v, scale, causal)
    from jax.experimental.pallas import tpu as pltpu
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               blk_q=blk_q, blk_k=blk_k)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // blk_q, Tk // blk_k),
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, blk_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, blk_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),    # acc
            pltpu.VMEM((blk_q, 128), jnp.float32),  # running max (lane-bcast)
            pltpu.VMEM((blk_q, 128), jnp.float32),  # running sum
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D)


def _fa_fwd(q, k, v, scale, causal, blk_q, blk_k):
    o = _flash_attention(q, k, v, scale, causal, blk_q, blk_k)
    return o, (q, k, v, o)


def _fa_bwd(scale, causal, blk_q, blk_k, res, g):
    """Blockwise recompute backward: lax.map over q blocks keeps peak
    score memory at O(blk_q · Tk) per (batch, head).

    Flash backward identities (FlashAttention paper, §B):
      P = softmax(S);  D_i = rowsum(dO ∘ O)
      dV = Pᵀ dO;  dS = P ∘ (dO Vᵀ − D_i);  dQ = dS K · scale;  dK = dSᵀ Q · scale
    """
    q, k, v, o = res
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    blk = blk_q if Tq % blk_q == 0 else Tq
    nq = Tq // blk

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    of = o.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    def per_head(q1, k1, v1, o1, g1):
        # (Tq,D),(Tk,D),... for one (batch,head)
        delta = jnp.sum(g1 * o1, axis=-1)                     # (Tq,)

        def q_block(i):
            qs = jax.lax.dynamic_slice_in_dim(q1, i * blk, blk)
            gs = jax.lax.dynamic_slice_in_dim(g1, i * blk, blk)
            ds = jax.lax.dynamic_slice_in_dim(delta, i * blk, blk)
            s = qs @ k1.T * scale                             # (blk, Tk)
            if causal:
                q_pos = i * blk + jnp.arange(blk)
                mask = q_pos[:, None] >= jnp.arange(Tk)[None, :]
                s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            dp = gs @ v1.T                                    # (blk, Tk)
            dsoft = p * (dp - ds[:, None])
            dq = dsoft @ k1 * scale                           # (blk, D)
            dk = dsoft.T @ qs * scale                         # (Tk, D)
            dv = p.T @ gs                                     # (Tk, D)
            return dq, dk, dv

        dqs, dks, dvs = jax.lax.map(q_block, jnp.arange(nq))
        return dqs.reshape(Tq, D), dks.sum(0), dvs.sum(0)

    flat = lambda a: a.reshape(B * H, a.shape[2], a.shape[3])
    dq, dk, dv = jax.vmap(per_head)(flat(qf), flat(kf), flat(vf),
                                    flat(of), flat(gf))
    return (dq.reshape(q.shape).astype(q.dtype),
            dk.reshape(k.shape).astype(k.dtype),
            dv.reshape(v.shape).astype(v.dtype))


_flash_attention.defvjp(_fa_fwd, _fa_bwd)


@register("_contrib_flash_attention", input_names=("q", "k", "v"),
          aliases=("flash_attention",),
          args=[Arg("causal", bool, False), Arg("scale", float, -1.0),
                Arg("block_q", int, 128), Arg("block_k", int, 128)])
def _flash_attention_op(p, q, k, v):
    """Memory-efficient attention: q/k/v (B, H, T, D) → (B, H, T, D)."""
    scale = p["scale"] if p["scale"] > 0 else q.shape[-1] ** -0.5
    blk_q = min(p["block_q"], q.shape[2])
    blk_k = min(p["block_k"], k.shape[2])
    return _flash_attention(q, k, v, float(scale), bool(p["causal"]),
                            int(blk_q), int(blk_k))


@register("_contrib_mha_decode_step",
          input_names=("qkv", "k_cache", "v_cache", "pos"),
          aliases=("mha_decode_step",), f32_inputs=(3,),
          args=[Arg("num_heads", int, required=True),
                Arg("scale", float, -1.0), Arg("impl", str, "dense")],
          num_outputs=3, differentiable=False,
          sp_impls=("ring", "ulysses"))
def _mha_decode_step_op(p, qkv, kc, vc, pos):
    """One autoregressive attention step over a KV cache (inference).

    qkv: (B, 1, 3*D) — the current token's fused projections;
    k_cache/v_cache: (B, H, Tmax, dh) rolling caches; pos: (1,) the
    current position t.  Writes this token's K/V at column t
    (lax.dynamic_update_slice — the position is DATA, so one compiled
    program serves every step) and attends over columns <= t.  Returns
    (out (B, 1, D), new_k_cache, new_v_cache).  O(Tmax*D) per token vs
    the full re-forward's O(Tmax^2*D) — the long-context decode path
    the 2017 reference never needed (its RNNs carry state natively;
    for attention the cache IS that recurrent state).
    """
    B, _, D3 = qkv.shape
    H = p["num_heads"]
    D = D3 // 3
    dh = D // H
    x = qkv.reshape(B, 3, H, dh)                    # T=1 folded away
    q, k, v = x[:, 0], x[:, 1], x[:, 2]             # (B, H, dh)
    if p["impl"] not in ("dense", "ring", "ulysses"):
        raise ValueError(
            f"mha_decode_step impl={p['impl']!r}: choose 'dense', "
            "'ring' (sequence-sharded caches) or 'ulysses' "
            "(head-sharded caches)")
    if p["impl"] in ("ring", "ulysses"):
        # sharded caches over the ambient sp mesh: the cache never
        # leaves its shard.  ring = sequence-sharded columns with a
        # pmax/psum distributed softmax; ulysses = head-sharded
        # full-length caches with purely local attention per head
        from ..parallel import sequence_parallel as _sp
        mesh, axis = _sp.current_sp_scope()
        scale = p["scale"] if p["scale"] > 0 else dh ** -0.5
        cache_spec = ((None, None, axis, None) if p["impl"] == "ring"
                      else (None, axis, None, None))
        step_fn = (_sp.ring_decode_step_sharded if p["impl"] == "ring"
                   else _sp.ulysses_decode_step_sharded)
        eager = not isinstance(qkv, jax.core.Tracer)
        orig_dev = None
        if eager:
            orig_dev = _sp.single_device_of(qkv)
            q, k, v, pos = _sp.place_on_mesh(mesh, (q, k, v, pos))
            kc, vc = _sp.place_on_mesh(mesh, (kc, vc), spec=cache_spec)
        out, kc, vc = step_fn(q, k, v, kc, vc, pos, mesh,
                              axis_name=axis, scale=float(scale))
        if eager and orig_dev is not None:
            # only the attention OUTPUT returns to the caller's device
            # (it feeds single-device eager neighbors); the caches stay
            # SHARDED — they are the recurrent state of the decode
            # loop, and gathering them back each step would both defeat
            # the memory scaling and pay O(cache) transfers per token
            out = jax.device_put(out, orig_dev)  # graft-lint: disable=memory-hygiene
        return out.reshape(B, 1, D).astype(qkv.dtype), kc, vc
    t = pos.astype(jnp.int32).reshape(())
    zero = jnp.zeros((), jnp.int32)
    kc = jax.lax.dynamic_update_slice(
        kc, k[:, :, None, :].astype(kc.dtype), (zero, zero, t, zero))
    vc = jax.lax.dynamic_update_slice(
        vc, v[:, :, None, :].astype(vc.dtype), (zero, zero, t, zero))
    scale = p["scale"] if p["scale"] > 0 else dh ** -0.5
    # scores + softmax in f32 like every other attention path (the
    # flash kernel and the dense reference): bf16 near-ties must not
    # flip the greedy argmax vs the training forward
    s = jnp.einsum("bhd,bhtd->bht", q.astype(jnp.float32) * scale,
                   kc.astype(jnp.float32))
    s = jnp.where(jnp.arange(kc.shape[2])[None, None, :] <= t, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bht,bhtd->bhd", w, vc.astype(jnp.float32))
    return out.reshape(B, 1, D).astype(qkv.dtype), kc, vc


@register("_contrib_multihead_attention", input_names=("qkv",),
          aliases=("multihead_attention",),
          args=[Arg("num_heads", int, required=True),
                Arg("causal", bool, True), Arg("impl", str, "dense"),
                Arg("scale", float, -1.0)],
          sp_impls=("ring", "ulysses"))
def _multihead_attention_op(p, qkv):
    """Fused causal multi-head self-attention over packed projections.

    qkv: (B, T, 3*D) — the output of one Dense QKV projection; returns
    (B, T, D).  Registered as an op (not python in the gluon block) so the
    shape-dependent reshapes/masks live where shapes are always concrete —
    usable from symbol graphs and hybridized blocks.  impl='flash' routes
    to the Pallas kernel; 'dense' materializes scores (XLA fuses the
    softmax chain).
    """
    B, T, D3 = qkv.shape
    H = p["num_heads"]
    D = D3 // 3
    dh = D // H
    x = qkv.reshape(B, T, 3, H, dh).transpose(2, 0, 3, 1, 4)  # (3,B,H,T,dh)
    q, k, v = x[0], x[1], x[2]
    scale = p["scale"] if p["scale"] > 0 else dh ** -0.5
    if p["impl"] == "flash":
        out = _flash_attention(q, k, v, float(scale), bool(p["causal"]),
                               min(128, T), min(128, T))
    elif p["impl"] in ("ring", "ulysses"):
        # sequence parallelism as a first-class impl: the mesh comes
        # from the ambient parallel.sp_scope (captured at trace time);
        # K/V rotate over ICI (ring) or heads re-shard via all-to-all
        # (ulysses) — SURVEY.md §5's "exposed through the same
        # Module/Gluon APIs" leg
        from ..parallel import sequence_parallel as _sp
        mesh, axis = _sp.current_sp_scope()
        eager = not isinstance(q, jax.core.Tracer)
        orig_dev = None
        if eager:
            # eager arrays arrive committed to one device; place them
            # sequence-sharded on the scope's mesh for shard_map, and
            # bring the result back so downstream single-device eager
            # ops compose (a jitted sp model runs fully on the mesh)
            orig_dev = _sp.single_device_of(q)
            q, k, v = _sp.place_on_mesh(
                mesh, (q, k, v), spec=(None, None, axis, None))
        fn = (_sp.ring_attention_sharded if p["impl"] == "ring"
              else _sp.ulysses_attention_sharded)
        out = fn(q, k, v, mesh, axis_name=axis, causal=bool(p["causal"]),
                 scale=float(scale))
        if eager and orig_dev is not None:
            # transient D2D return-to-caller move (see ops/registry)
            out = jax.device_put(out, orig_dev)  # graft-lint: disable=memory-hygiene
    else:
        out = _dense_reference(q, k, v, float(scale), bool(p["causal"]))
    return out.transpose(0, 2, 1, 3).reshape(B, T, D)


@register("_contrib_arange_like", input_names=("data",),
          aliases=("arange_like",), differentiable=False,
          args=[Arg("axis", int, None), Arg("start", float, 0.0),
                Arg("step", float, 1.0)])
def _arange_like(p, x):
    """Parity: _contrib_arange_like — a [start, start+step, ...] ramp
    shaped like `data` along `axis` (or flat over all elements)."""
    if p["axis"] is None:
        n = 1
        for d in x.shape:
            n *= d
        return (p["start"] + p["step"] * jnp.arange(n)).reshape(x.shape)
    n = x.shape[p["axis"]]
    return p["start"] + p["step"] * jnp.arange(n, dtype=jnp.float32)
