"""gluon.data namespace (parity: python/mxnet/gluon/data/__init__.py)."""
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from .dataloader import DataLoader
from .prefetcher import AsyncPrefetcher, prefetch_to_device
from . import vision
