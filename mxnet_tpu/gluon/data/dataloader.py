"""gluon.data.DataLoader (parity: python/mxnet/gluon/data/dataloader.py:73-124).

The reference forks worker *processes* and ships batches through POSIX
shared memory (CPUSharedStorageManager).  Here workers are a thread pool:
batchification is numpy-side (releases the GIL) and the device transfer is a
single PJRT host-to-HBM DMA per batch — the multiprocess+shm design exists
to feed GPUs from python, which the TPU path doesn't need.  num_workers
keeps its meaning (parallel prefetch depth).
"""
from __future__ import annotations

import concurrent.futures as _futures
import time as _time

import numpy as _np

from ... import ndarray as nd
from ...ndarray import NDArray
from ...observability import metrics as _metrics
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples into a batch (parity: dataloader.default_batchify_fn).

    NDArray samples stack in ONE device-side dispatch — the old path paid
    a per-sample `asnumpy()` device→host sync plus a re-upload, which made
    batchification O(batch_size) blocking round trips on a tunneled TPU."""
    if isinstance(data[0], NDArray):
        from ...ndarray.sparse import BaseSparseNDArray
        if not any(isinstance(d, BaseSparseNDArray) for d in data):
            import jax.numpy as jnp
            if _metrics.ENABLED:
                _metrics.XLA_LAUNCHES.inc(kind="data")
            return NDArray(jnp.stack([d._data for d in data]),
                           data[0].context)
        # sparse samples: rows-only storage densifies through the host
        return nd.array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return nd.array(data, dtype=data.dtype)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                on = _metrics.ENABLED
                t0 = _time.perf_counter() if on else 0.0
                out = self._batchify_fn([self._dataset[idx] for idx in batch])
                if on:
                    _metrics.DATA_WAIT_SECONDS.observe(
                        _time.perf_counter() - t0)
                yield out
            return
        with _futures.ThreadPoolExecutor(self._num_workers) as pool:
            futures = [pool.submit(
                lambda b: self._batchify_fn([self._dataset[i] for i in b]),
                batch) for batch in self._batch_sampler]
            for fut in futures:
                # time the consumer-side stall, not the worker's build:
                # with enough workers this is ~0 even when batchify is slow
                on = _metrics.ENABLED
                t0 = _time.perf_counter() if on else 0.0
                out = fut.result()
                if on:
                    _metrics.DATA_WAIT_SECONDS.observe(
                        _time.perf_counter() - t0)
                yield out

    def __len__(self):
        return len(self._batch_sampler)


# parity alias: the reference's multiprocessing batchify is the same
# stacking logic (shared-memory pickling is a CUDA-host concern the
# jax.Array path doesn't have)
default_mp_batchify_fn = default_batchify_fn
