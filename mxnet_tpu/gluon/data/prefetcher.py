"""Prefetch-to-device pipeline: overlap the host→HBM transfer of batch N+1
with the compute of batch N.

The reference hides input latency with dmlc::ThreadedIter double-buffering
(src/io/iter_prefetcher.h); the TPU analog is a background thread that
`jax.device_put`s the NEXT batch while the current step's XLA programs run,
so the training loop's queue.get is ~0 when the pipeline keeps up.  The
consumer-side stall is measured by the `mxnet_prefetch_wait_seconds`
histogram; transfers are accounted as kind="data" launches (excluded from
per-step dispatch deltas — they are issued mid-step by the producer thread)
plus `mxnet_device_transfer_bytes_total`.

`AsyncPrefetcher` is the shared core (also backing `io.PrefetchingIter`);
`prefetch_to_device(it, depth=2)` is the user-facing wrapper for any batch
iterable (DataLoader, DataIter, generator).
"""
from __future__ import annotations

import logging
import queue as _queue
import threading
import time as _time

from ...analysis import sanitizer as _san
from ...base import getenv
from ...faultinject import fire as _fi_fire
from ...observability import flight as _flight
from ...observability import memory as _memory
from ...observability import metrics as _metrics
from ...resilience import (DataCorruptionError, DataSkipBudgetError,
                           classify as _classify, TRANSIENT as _TRANSIENT)

log = logging.getLogger(__name__)

# end-of-stream sentinel (not None: sources may legitimately yield None)
_END = object()

_live_prefetchers = None  # weakref.WeakSet, created lazily


def _register(p) -> None:
    """Track live prefetchers and stop them atexit: a daemon worker
    mid-XLA-dispatch at interpreter teardown aborts the process
    ('terminate called without an active exception')."""
    global _live_prefetchers
    if _live_prefetchers is None:
        import atexit
        import weakref
        _live_prefetchers = weakref.WeakSet()
        atexit.register(_close_live_prefetchers)
    _live_prefetchers.add(p)


def _close_live_prefetchers() -> None:
    for p in list(_live_prefetchers or ()):
        try:
            p.close()
        except Exception:
            pass


class AsyncPrefetcher:
    """Bounded background-thread prefetch over a `next()`-style source.

    The worker calls `next_fn()` (StopIteration ends the stream), applies
    `transform` (e.g. device placement) still on the worker thread, and
    feeds a queue of `depth` ready batches.  Worker exceptions re-raise in
    the consumer on `get()`, followed by StopIteration — a consumer that
    swallows the error won't hang.

    Fault containment (ISSUE 12; docs/training_resilience.md):

    * a TRANSIENT IO error from the source (resilience.classify —
      OSError/timeout/UNAVAILABLE, or an injected `data.batch` fault)
      respawns the worker ONCE per prefetcher after a short backoff
      (`mxnet_prefetch_respawns_total`); a second transient surfaces to
      the consumer exactly as before.
    * a `DataCorruptionError` (undecodable record) is SKIPPED while the
      `skip_budget` lasts (default `MXNET_DATA_SKIP_BUDGET`, 0 = every
      corrupt record surfaces); each skip counts
      `mxnet_data_records_skipped_total`, and exhausting the budget
      surfaces a typed `DataSkipBudgetError` — one bad record can't
      kill an epoch, but systemically damaged data still fails loudly."""

    _MAX_RESPAWNS = 1
    _RESPAWN_BACKOFF_S = 0.05

    def __init__(self, next_fn, depth=None, transform=None,
                 observe_wait: bool = False, skip_budget=None):
        self._next_fn = next_fn
        self._transform = transform
        # default depth 2 unless MXNET_PREFETCH_DEPTH overrides it (the
        # autotuner exports depth>=K so a K-superstep consumer always
        # finds its whole batch group staged); an explicit arg wins
        if depth is None:
            depth = int(getenv("MXNET_PREFETCH_DEPTH", 2))
        self._skip_budget = int(getenv("MXNET_DATA_SKIP_BUDGET", 0)) \
            if skip_budget is None else int(skip_budget)
        self.respawns = 0
        self.skipped = 0
        # prefetch_to_device consumers observe their stalls into the
        # prefetch_wait histogram; io.PrefetchingIter keeps recording
        # into DATA_WAIT_SECONDS itself — one histogram per wait, never
        # both
        self._observe_wait = observe_wait
        self._depth = max(1, int(depth))
        self._queue: _queue.Queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._done = False
        # guards self._thread: written by close() (consumer) AND by the
        # respawn path (worker hands the stream to its replacement)
        self._tlock = _san.make_lock("prefetcher.thread")
        _register(self)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                # chaos site: fires before the source read, so an
                # injected raise models a read that failed WITHOUT
                # consuming a record (a skip/respawn then re-reads the
                # same record — the stream content is unchanged); a real
                # decoder raising DataCorruptionError mid-read genuinely
                # drops that record
                _fi_fire("data.batch")
                item = self._next_fn()
                if self._transform is not None:
                    # device placement (h2d) happens HERE on the worker
                    # thread — the flight span attributes the transfer
                    # to the producer, not the consumer's wait (and the
                    # ledger attributes the staged batch to "prefetch")
                    with _flight.phase_span("prefetch_h2d", cat="io",
                                            mem=True), \
                            _memory.memory_scope("prefetch"):
                        item = self._transform(item)
            except StopIteration:
                self._queue.put(_END)
                return
            except DataCorruptionError as e:
                if self.skipped < self._skip_budget:
                    self.skipped += 1
                    if _metrics.ENABLED:
                        _metrics.DATA_RECORDS_SKIPPED.inc()
                    log.warning(
                        "prefetcher: skipping corrupt record (%s) — "
                        "%d/%d of MXNET_DATA_SKIP_BUDGET used", e,
                        self.skipped, self._skip_budget)
                    continue
                if self._skip_budget == 0:
                    err: BaseException = e  # skipping never opted into
                else:
                    err = DataSkipBudgetError(
                        f"corrupt-record skip budget exhausted "
                        f"({self._skip_budget} records already skipped; "
                        f"next: {e}) — the input data is damaged beyond "
                        "MXNET_DATA_SKIP_BUDGET")
                    err.__cause__ = e
                self._queue.put(err)
                self._queue.put(_END)
                return
            except BaseException as e:  # surface in the consumer thread
                if self.respawns < self._MAX_RESPAWNS and \
                        not self._stop.is_set() and \
                        _classify(e) is _TRANSIENT:
                    # transient source hiccup (flaky NFS, dropped
                    # connection, injected chaos): hand the stream to a
                    # fresh worker once instead of killing the epoch
                    self.respawns += 1
                    if _metrics.ENABLED:
                        _metrics.PREFETCH_RESPAWNS.inc()
                    log.warning(
                        "prefetcher: worker hit transient %s: %s — "
                        "respawning (%d/%d)", type(e).__name__, e,
                        self.respawns, self._MAX_RESPAWNS)
                    _time.sleep(self._RESPAWN_BACKOFF_S)
                    t = threading.Thread(target=self._worker, daemon=True)
                    with self._tlock:
                        if self._stop.is_set():
                            return  # closed during the backoff window
                        self._thread = t
                    t.start()
                    return
                self._queue.put(e)
                self._queue.put(_END)
                return
            self._queue.put(item)

    def get(self):
        """Next ready batch; blocks only when the pipeline is behind (the
        stall is the prefetch_wait histogram).  Exhaustion is sticky:
        every get() after the stream ends raises StopIteration instead
        of blocking on the drained queue."""
        if self._done:
            raise StopIteration
        on = _metrics.ENABLED and self._observe_wait
        t0 = _time.perf_counter() if on else 0.0
        with _flight.phase_span("prefetch_wait", cat="io"):
            item = self._queue.get()
        if on:
            _metrics.PREFETCH_WAIT_SECONDS.observe(_time.perf_counter() - t0)
        if item is _END:
            self._done = True
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        """Stop the worker and drain the buffer (idempotent); any later
        get() raises StopIteration."""
        self._done = True
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        with self._tlock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _resolve_device(device):
    """Context / jax.Device / None -> (jax.Device, Context label).

    The Context is what placed NDArrays report as `.context` — it must
    name the DESTINATION device, or downstream `as_in_context` calls
    would see a mismatch and re-copy the batch the prefetch just moved."""
    from ...context import Context, _local, current_context
    if device is None:
        ctx = current_context()
        return ctx.jax_device(), ctx
    if hasattr(device, "jax_device"):
        return device.jax_device(), device
    plat = getattr(device, "platform", "cpu")
    kind = "cpu" if plat == "cpu" else "tpu"
    try:
        idx = _local(plat).index(device)
    except ValueError:
        idx = 0
    return device, Context(kind, idx)


def _device_put_batch(batch, dev, ctx):
    """Recursively move a batch (NDArray / DataBatch / list / tuple /
    numpy) onto `dev`, labelling results with `ctx`.  Already-placed
    arrays pass through untouched."""
    import jax

    from ...ndarray import NDArray

    def leaf(x):
        if isinstance(x, NDArray):
            from ...ndarray.sparse import BaseSparseNDArray
            if isinstance(x, BaseSparseNDArray):
                return x  # rows-only storage is host-orchestrated
            d = x._data
            if dev in getattr(d, "devices", lambda: set())():
                return x
            if _metrics.ENABLED:
                _metrics.XLA_LAUNCHES.inc(kind="data")
                _metrics.TRANSFER_BYTES.inc(int(getattr(d, "nbytes", 0) or 0))
            return NDArray(jax.device_put(d, dev), ctx)
        if isinstance(x, (list, tuple)):
            return type(x)(leaf(v) for v in x)
        if hasattr(x, "data") and hasattr(x, "label"):  # io.DataBatch
            x.data = [leaf(v) for v in x.data]
            if x.label is not None:
                x.label = [leaf(v) for v in x.label]
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):  # numpy / jax array
            if _metrics.ENABLED:
                _metrics.XLA_LAUNCHES.inc(kind="data")
                _metrics.TRANSFER_BYTES.inc(int(getattr(x, "nbytes", 0) or 0))
            return NDArray(jax.device_put(x, dev), ctx)
        return x

    return leaf(batch)


class _DevicePrefetchIter:
    """Iterator returned by prefetch_to_device: double-buffers device
    placement of upcoming batches in a background thread."""

    def __init__(self, source, depth=None, device=None,
                 skip_budget=None):
        self._source = source
        self._depth = depth
        self._skip_budget = skip_budget
        self._dev, self._ctx = _resolve_device(device)
        self._pf = None
        self._start()

    def _start(self) -> None:
        src = self._source
        next_fn = src.next if hasattr(src, "next") and not hasattr(src, "__next__") \
            else iter(src).__next__
        self._pf = AsyncPrefetcher(
            next_fn, depth=self._depth,
            transform=lambda b: _device_put_batch(b, self._dev, self._ctx),
            observe_wait=True, skip_budget=self._skip_budget)

    def __iter__(self):
        return self

    def __next__(self):
        if self._pf is None:
            raise StopIteration
        return self._pf.get()

    next = __next__

    def reset(self) -> None:
        """Restart the underlying source (DataIter protocol)."""
        self.close()
        if hasattr(self._source, "reset"):
            self._source.reset()
        self._start()

    def close(self) -> None:
        if self._pf is not None:
            self._pf.close()
            self._pf = None

    def __len__(self):
        return len(self._source)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(data_iter, depth=None, device=None,
                       skip_budget=None):
    """Wrap a batch iterable so the next `depth` batches are device-resident
    before the training loop asks for them.

    >>> for batch in prefetch_to_device(loader, depth=2):
    ...     trainer.step(...)   # batch N+1 uploads while step N runs

    depth: queue depth; None reads MXNET_PREFETCH_DEPTH (default 2 —
    the autotuner exports depth>=K when a K-superstep decision lands,
    so the whole K-batch group stages ahead of the scan dispatch).
    device: a Context, a jax.Device, or None (the current context's device).
    skip_budget: corrupt-record tolerance (default MXNET_DATA_SKIP_BUDGET)
    — see AsyncPrefetcher.
    """
    return _DevicePrefetchIter(data_iter, depth=depth, device=device,
                               skip_budget=skip_budget)
