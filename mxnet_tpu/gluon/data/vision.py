"""gluon.data.vision datasets (parity: python/mxnet/gluon/data/vision.py).

MNIST/FashionMNIST read idx files, CIFAR10/100 read the python-pickle batches
— from a local `root` directory (zero-egress environments stage files there;
`download` is attempted only if files are missing).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from ...base import MXNetError
from ... import ndarray as nd
from .dataset import Dataset, RecordFileDataset
from ... import recordio
from ...io import _read_idx


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            os.makedirs(self._root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._base_names = (("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
                            if train else
                            ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"))
        super().__init__(root, train, transform)

    def _get_data(self):
        img_name, lab_name = self._base_names
        paths = []
        for name in (img_name, lab_name):
            p = os.path.join(self._root, name)
            if not os.path.exists(p) and os.path.exists(p + ".gz"):
                p = p + ".gz"
            if not os.path.exists(p):
                raise MXNetError(
                    f"MNIST file {p} not found; stage the idx files under "
                    f"{self._root} (no network in this environment)")
            paths.append(p)
        data = _read_idx(paths[0])
        label = _read_idx(paths[1])
        self._data = nd.array(data.reshape(-1, 28, 28, 1).astype(_np.float32)
                              / 255.0)
        self._label = label.astype(_np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        batches = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        data_list, label_list = [], []
        base = self._root
        sub = os.path.join(base, "cifar-10-batches-py")
        if os.path.isdir(sub):
            base = sub
        for b in batches:
            p = os.path.join(base, b)
            if not os.path.exists(p):
                raise MXNetError(
                    f"CIFAR10 batch {p} not found; stage cifar-10-batches-py "
                    f"under {self._root}")
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            data_list.append(d[b"data"].reshape(-1, 3, 32, 32))
            label_list.append(_np.asarray(d[b"labels"]))
        data = _np.concatenate(data_list).transpose(0, 2, 3, 1)
        self._data = nd.array(data.astype(_np.float32) / 255.0)
        self._label = _np.concatenate(label_list).astype(_np.int32)


class CIFAR100(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar100", fine_label=True,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        name = "train" if self._train else "test"
        base = self._root
        sub = os.path.join(base, "cifar-100-python")
        if os.path.isdir(sub):
            base = sub
        p = os.path.join(base, name)
        if not os.path.exists(p):
            raise MXNetError(f"CIFAR100 file {p} not found")
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._data = nd.array(data.astype(_np.float32) / 255.0)
        self._label = _np.asarray(d[key]).astype(_np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Dataset over a .rec of packed images (parity: vision.ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        record = super().__getitem__(idx)
        header, img_bytes = recordio.unpack(record)
        img = recordio._imdecode_bytes(img_bytes, self._flag)
        img = nd.array(img)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Dataset over a folder of class subfolders (parity: vision.ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        fname, label = self.items[idx]
        with open(fname, "rb") as f:
            img = recordio._imdecode_bytes(f.read(), self._flag)
        img = nd.array(img)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
