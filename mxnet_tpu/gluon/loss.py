"""gluon losses (parity: python/mxnet/gluon/loss.py:66-656).

L2, L1, SigmoidBinaryCrossEntropy, SoftmaxCrossEntropy, KLDiv, CTC, Huber,
Hinge, SquaredHinge, Logistic, Triplet — all HybridBlocks over F ops.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .block import HybridBlock


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape) if hasattr(y, "shape") and not _is_sym(x) \
        else F.reshape_like(x, y)


def _is_sym(x):
    from ..symbol import Symbol
    return isinstance(x, Symbol)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, " \
               f"w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


def _mean_all_but_batch(F, loss, batch_axis):
    return F.mean(loss, axis=batch_axis, exclude=True)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # log(1+exp(x)) - x*y, stable form
            loss = F.relu(pred) - pred * label + \
                F.Activation(F.abs(pred) * -1.0, act_type="softrelu")
        else:
            loss = -(F.log(pred + 1e-12) * label +
                     F.log(1.0 - pred + 1e-12) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification loss (parity: gluon/loss.py:398,
    backed by src/operator/contrib/ctc_loss.cc in the reference; here the
    registered `_contrib_ctc_loss` op — optax's XLA ctc_loss — so gradients
    flow through the autograd tape in both eager and symbol modes)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)  # op wants (T, N, C)
        if self._label_layout == "TN":
            label = F.swapaxes(label, 0, 1)
        kw = {}
        if pred_lengths is not None:
            kw["data_lengths"] = pred_lengths
        if label_lengths is not None:
            kw["label_lengths"] = label_lengths
        loss = F.CTCLoss(pred, label,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         blank_label="last", **kw)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ["signed", "binary"]:
            raise ValueError(f"Unsupported label_format {label_format}")

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(F.abs(pred) * -1.0, act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _mean_all_but_batch(F, loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(pred - positive) - F.square(pred - negative),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)
