"""Whole-step compilation + mixed precision for the Gluon hot loop.

PRs 2-3 left a dense hybridized model's training step at 3-4
steady-state XLA dispatches: fwd (CachedOp), bwd (vjp program),
bucketed allreduce, fused update.  Every remaining boundary is a
Python round trip through the TPU tunnel and a lost cross-stage fusion
opportunity — the TVM (arxiv 1802.04799) / TPU-MLIR (arxiv 2210.15016)
observation that the next hot-path win is compiling MORE of the step.

``WholeStepCompiler`` traces forward + loss + backward + bucketed
reduce (+ 2-bit quantize/dequantize against the Trainer's flat
error-feedback residuals) + the ``FusedUpdater`` optimizer math into
ONE ``jax.jit`` program with parameters, optimizer state, residuals,
aux state, and loss-scaler state DONATED: a steady-state training step
is **1 XLA dispatch** regardless of parameter count.  Opt-in via
``MXNET_WHOLE_STEP=1``; any unsupported construct — sparse params,
``update_on_kvstore``, multi-host kvstore, custom/non-differentiable
ops, non-``write`` grad_req, multi-device copies, a loss that cannot
compose symbolically — falls back to the PR 2 fused path (<= 4
dispatches) with a single warning.

Mixed precision rides the same program (``MXNET_AMP=bf16|fp16``):
matmul / conv / deconv compute autocasts to the low-precision dtype
inside the compiled step (per-op cast-in/cast-out over
``AMP_COMPUTE_OPS``; f32 master weights and optimizer state never
leave f32, so the backward's matmuls run low-precision too via the
cast vjp).  ``fp16`` adds dynamic loss scaling: scale/unscale,
nonfinite detection, skip-step, and scale growth/backoff
(``MXNET_LOSS_SCALE_INIT`` / ``MXNET_LOSS_SCALE_WINDOW``) all trace
into the same program; the scaler state is device-resident, donated,
and rides ``Trainer.save_states`` / ``load_states`` (and therefore
``mx.checkpoint.save_trainer``).

Numerics: the f32 whole-step program runs the exact op sequence of the
fused path (same GraphPlan, same bucket layout, same
quantize/dequantize math, same fused_step) — tests/test_wholestep.py
pins bitwise agreement over 5 steps on its nets.  (XLA may fuse the
single program differently than the fused path's separate programs, so
arbitrary models get f32 ulp-level agreement, not a bitwise
guarantee.)  Under fp16 skip-steps the
python-side ``num_update`` (lr schedules) still advances while the
device-side bias-correction counter ``t`` advances only on applied
steps — the numerically correct behavior for Adam-family optimizers.
"""
from __future__ import annotations

import itertools
import logging

import jax
import jax.numpy as jnp
import numpy as _np

from ..analysis import hot_path
from ..analysis import sanitizer as _san
from ..base import MXNetError, getenv
from ..faultinject import InjectedFault as _InjectedFault
from ..faultinject import fire as _fi_fire
from ..ndarray import NDArray
from ..resilience import DeviceUnavailableError as _DeviceUnavailableError
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability import introspect as _introspect
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from ..observability.tracing import trace_span
from ..optimizer import HyperDeviceCache as _HyperDeviceCache
from ..optimizer import cast_like as _cast_like
from .. import symbol as sym_mod
from ..symbol.graph import GraphPlan
from .. import autograd
from .parameter import DeferredInitializationError

logger = logging.getLogger("mxnet_tpu.gluon.wholestep")

# internal graph-input names for the step's data/label feeds — namespaced
# so they can never collide with a parameter name
_DATA = "__wholestep_data__"
_LABEL = "__wholestep_label__"

# ops whose compute autocasts to the low-precision dtype under MXNET_AMP
# (the flops carriers; everything else — norms, softmax, loss, optimizer
# — stays f32).  Inputs flagged f32-forced by the op registry
# (Operator.f32_inputs) are never cast.
AMP_COMPUTE_OPS = frozenset({
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
})

_LP_DTYPES = {"bf16": jnp.bfloat16, "fp16": jnp.float16}

# install the donation-noise filter ONCE per process, not per compiler:
# repeated unguarded filterwarnings() calls grow warnings.filters without
# bound (same expected-noise rationale as CachedOp's filter in block.py)
_donation_filter_installed = False


def _install_donation_filter():
    global _donation_filter_installed
    if not _donation_filter_installed:
        import warnings as _warnings
        _warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        _donation_filter_installed = True

# process-unique id per traced graph, used in the compiled-program cache
# key: the cache (FusedUpdater._fn_cache) outlives any one compiler, so
# keying on id(plan) could alias a NEW graph onto a dead one's recycled
# address and silently run the wrong program
_PLAN_UID = itertools.count(1)
_SCALE_GROWTH = 2.0
_SCALE_BACKOFF = 0.5
_SCALE_MAX = float(2 ** 24)


def amp_policy() -> str:
    """Resolve MXNET_AMP to a dtype policy ("f32" | "bf16" | "fp16")."""
    raw = str(getenv("MXNET_AMP", "")).strip().lower()
    if raw in ("", "0", "off", "none", "f32", "fp32", "float32"):
        return "f32"
    if raw in ("bf16", "bfloat16"):
        return "bf16"
    if raw in ("fp16", "f16", "float16"):
        return "fp16"
    raise MXNetError(
        f"MXNET_AMP={raw!r} not understood (use bf16, fp16, or off)")


def _amp_overrides(plan: GraphPlan, lp):
    """step_overrides for GraphPlan.run that autocast AMP_COMPUTE_OPS:
    f32 float inputs cast to ``lp`` for the op's compute, outputs cast
    back to f32 so the surrounding graph (activations, norms, loss) is
    unchanged.  jax.vjp of the cast pair makes the op's BACKWARD
    matmuls low-precision too, with f32 gradients delivered to the
    optimizer."""
    over = {}
    for si, step in enumerate(plan.steps):
        if step.op.name not in AMP_COMPUTE_OPS:
            continue
        keep32 = frozenset(step.op.f32_inputs)

        def _run(p, ins, _op=step.op, _keep=keep32):
            cast = [a.astype(lp)
                    if (i not in _keep and a is not None
                        and getattr(a, "dtype", None) == jnp.float32)
                    else a
                    for i, a in enumerate(ins)]
            out = _op.fn(p, *cast)
            out = out if isinstance(out, tuple) else (out,)
            return tuple(o.astype(jnp.float32)
                         if getattr(o, "dtype", None) == lp else o
                         for o in out)

        over[si] = _run
    return over


class _Ineligible(RuntimeError):
    """Construct the whole-step tracer cannot compile — fall back."""


class _AmpIneligible(_Ineligible):
    """MXNET_AMP cannot apply to this model — a CONFIG-dependent
    condition, so it falls back per-step (re-checked on every call)
    instead of permanently demoting a compiler whose f32 program may be
    built and working; unsetting MXNET_AMP resumes whole-step."""


class _ShardIneligible(_Ineligible):
    """THIS step cannot dispatch sharded (e.g. a ragged final batch
    that does not divide the mesh's data axis) — a per-batch condition,
    handled like _AmpIneligible: fall back for this call only, the next
    full batch runs the sharded program again."""


def _sel(finite, new, old):
    """Per-leaf where(finite, new, old) tolerant of None / nested
    tuple states (the fp16 skip-step select)."""
    if new is None or old is None:
        return new
    if isinstance(new, (tuple, list)):
        return type(new)(_sel(finite, a, b) for a, b in zip(new, old))
    return jnp.where(finite, new, old)


# the dtype-preservation rule is SHARED with FusedUpdater.update_all
# (optimizer.cast_like) — the whole-step/fused bitwise-parity contract
# depends on both paths casting identically


class WholeStepCompiler:
    """One donated XLA program per Gluon training step.

    ::

        stepper = mx.gluon.wholestep.WholeStepCompiler(net, loss_fn,
                                                       trainer)
        for x, y in batches:
            loss = stepper.step(x, y)          # per-sample loss NDArray

    ``step`` runs the single compiled whole-step program when
    ``MXNET_WHOLE_STEP=1`` and the model/trainer are eligible, and the
    classic record/backward/``Trainer.step`` fused path otherwise —
    the returned loss and the training trajectory are identical in f32
    either way.  ``net`` must be a ``HybridBlock`` (hybridized or not;
    the compiler traces its own graph) and ``loss_fn`` a HybridBlock
    loss taking ``(pred, label)``.
    """

    def __init__(self, net, loss_fn, trainer, mesh=None):
        self.net = net
        self.loss_fn = loss_fn
        self.trainer = trainer
        # GSPMD mesh: explicit arg > the trainer's mesh > the ambient
        # parallel.mesh.current_mesh() (which itself reads
        # MXNET_MESH_BATCH/MODEL).  Resolved once at build time so the
        # frozen program and its committed placements agree; None keeps
        # the replicated path bit-for-bit untouched.
        self._mesh_arg = mesh
        self.mesh = None
        self._built = None
        self._fallback_reason = None  # permanent-fallback explanation
        self._warned = False
        # lr/wd last-value cache + device-resident step counter: the
        # SAME implementation FusedUpdater.hyper_arrays uses (bitwise
        # parity between step modes depends on identical seeding)
        self._hyper_cache = _HyperDeviceCache()
        # once the program has executed successfully, runtime failures
        # (OOM included) must PROPAGATE, not silently fall back — the
        # failed call may already have invalidated donated buffers, so
        # re-running the step eagerly is not safe
        self._ran = False
        self._amp_warned = False       # AMP-ineligible model, warn once
        self._amp_env_checked = False  # AMP-without-whole-step, warn once
        self._shard_warned = False     # per-step shard fallback, once
        self._mesh_comp_warned = False  # compression off on mesh, once
        # introspection captures done, per (program cache key, data
        # shape) — a new shape re-notes so the recorded flops track the
        # running batch size
        self._noted_keys = set()
        # backends without real donation (CPU) warn per trace; the user
        # opted into best-effort donation, so this is expected noise
        _install_donation_filter()

    # -- public entry --------------------------------------------------------
    @hot_path
    def step(self, data, label, batch_size=None):
        """One full training step on (data, label); returns the loss
        NDArray (per-sample, exactly what ``loss_fn(net(data), label)``
        returns on the fallback path).  Steady state: 1 XLA dispatch
        when whole-step is active, <= 4 on the fallback path."""
        bs = batch_size if batch_size is not None else int(data.shape[0])
        if self._fallback_reason is not None:
            return self._fallback(data, label, bs)
        if not getenv("MXNET_WHOLE_STEP", False):
            self._warn_amp_without_wholestep()
            return self._fallback(data, label, bs)
        if autograd.is_recording():
            raise MXNetError(
                "WholeStepCompiler.step() must not be called inside "
                "autograd.record() — it manages forward/backward itself")
        policy = amp_policy()
        try:
            built = self._ensure_built()
            return self._run(built, data, label, bs, policy)
        except DeferredInitializationError:
            # shapes materialize on the eager path; retry the build on
            # the next step
            return self._fallback(data, label, bs)
        except _AmpIneligible as e:
            # config-dependent, NOT permanent: re-checked every step, so
            # unsetting MXNET_AMP resumes the whole-step program
            if not self._amp_warned:
                logger.warning(
                    "MXNET_AMP requested but %s — running the fused f32 "
                    "path while the policy is set", e)
                self._amp_warned = True
            return self._fallback(data, label, bs)
        except _ShardIneligible as e:
            # per-batch, NOT permanent: a ragged final batch runs the
            # fused path once; the next full batch dispatches sharded
            if not self._shard_warned:
                logger.warning(
                    "sharded whole-step skipped for this batch (%s) — "
                    "running the fused path for it", e)
                self._shard_warned = True
            return self._fallback(data, label, bs)
        except _Ineligible as e:
            self._note_fallback(str(e))
            return self._fallback(data, label, bs)
        except Exception as e:  # noqa: BLE001 — tracing arbitrary user graphs
            if self._ran or self._is_execution_failure(e) \
                    or self._is_transient(e):
                # runtime failure (e.g. the typed OOM that
                # memory.oom_guard re-raises after its post-mortem): the
                # counters were rolled back by _run, but the failed call
                # may have consumed donated buffers — eagerly retrying
                # could read dead arrays, and the user must see the
                # error.  Applies on the FIRST call too: jit executes
                # (and donates) right after tracing, so an
                # execution-typed error means buffers were at risk even
                # though _ran is still False
                raise
            self._note_fallback(f"{type(e).__name__}: {e}")
            return self._fallback(data, label, bs)

    @staticmethod
    def _is_execution_failure(e: Exception) -> bool:
        """True when the exception came from EXECUTING the compiled
        program (device OOM, XLA runtime) rather than from tracing it —
        execution implies the donated buffers were in play, so eager
        fallback is unsafe; trace failures happen before donation and
        may fall back freely."""
        if isinstance(e, (_memory.DeviceMemoryError,
                          _memory.HBMBudgetError)):
            return True
        # injected faults and transient device losses (the resilience
        # taxonomy's "transient" class) must NEVER demote the compiler
        # to a permanent fused fallback: the condition is recoverable —
        # propagate so a TrainingSupervisor (or the user) can restore
        # state and retry the same whole-step program
        if isinstance(e, (_InjectedFault, _DeviceUnavailableError)):
            return True
        if type(e).__name__ == "XlaRuntimeError":
            return True
        return "RESOURCE_EXHAUSTED" in str(e) or "UNAVAILABLE" in str(e)

    @staticmethod
    def _is_transient(e: Exception) -> bool:
        """The resilience taxonomy's transient class (plain OSError /
        ConnectionError / timeout included): RECOVERABLE conditions
        must propagate — even on the first call, before ``_ran`` —
        never permanently demote the compiler to the fused fallback."""
        from ..resilience import TRANSIENT, classify
        return classify(e) is TRANSIENT

    __call__ = step

    @property
    def active(self) -> bool:
        """True once a whole-step program has been built and no
        permanent fallback was taken."""
        return self._built is not None and self._fallback_reason is None

    @property
    def fallback_reason(self):
        return self._fallback_reason

    # -- fallback (the PR 2 fused path) --------------------------------------
    def _fallback(self, data, label, batch_size):
        # the fused/legacy path always runs f32 optimizer math — clear
        # any sticky whole-step AMP policy so update_all never keys
        # (and loudly "recompiles") under a precision it never traced
        for u in getattr(self.trainer, "_updaters", None) or []:
            if getattr(u, "dtype_policy", "f32") != "f32":
                u.dtype_policy = "f32"
        if self.mesh is not None and self.mesh.size > 1:
            # params already committed to the mesh: replicate the batch
            # onto it so the eager CachedOp jit sees ONE device set (a
            # ragged _ShardIneligible batch lands here; every device
            # computes the full batch — slower, but correct)
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            from ..ndarray import NDArray as _ND
            repl = NamedSharding(self.mesh, PartitionSpec())
            data = _ND(jax.device_put(data._data, repl), data.context)  # graft-lint: disable=memory-hygiene
            label = _ND(jax.device_put(label._data, repl), label.context)  # graft-lint: disable=memory-hygiene
        with autograd.record():
            out = self.net(data)
            loss = self.loss_fn(out, label)
        loss.backward()
        self.trainer.step(batch_size)
        return loss

    def _warn_amp_without_wholestep(self) -> None:
        """MXNET_AMP only exists inside the whole-step program; setting
        it without MXNET_WHOLE_STEP=1 silently trains f32 — say so."""
        if self._amp_env_checked:
            return
        self._amp_env_checked = True
        try:
            policy = amp_policy()
        except MXNetError:
            return
        if policy != "f32":
            logger.warning(
                "MXNET_AMP=%s is set but MXNET_WHOLE_STEP is not enabled "
                "— autocast and loss scaling only exist inside the "
                "whole-step program; training runs full f32", policy)

    def _note_fallback(self, reason: str) -> None:
        self._fallback_reason = reason
        if not self._warned:
            try:
                policy = amp_policy()
            except MXNetError:
                policy = "f32"
            amp_note = "" if policy == "f32" else (
                f"; MXNET_AMP={policy} is INERT on the fallback path — "
                "training runs full f32 with no loss scaling")
            logger.warning(
                "MXNET_WHOLE_STEP=1 requested but this model/trainer is "
                "not whole-step compilable (%s) — using the fused "
                "multi-program path%s", reason, amp_note)
            self._warned = True

    # -- build ---------------------------------------------------------------
    def _ensure_built(self):
        if self._built is not None:
            return self._built
        tr = self.trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        self._check_trainer(tr)
        from ..parallel import mesh as _pmesh
        self.mesh = _pmesh.resolve_mesh(
            self._mesh_arg if self._mesh_arg is not None
            else getattr(tr, "_mesh", None))
        plan, out_sym = self._trace_graph()
        built = self._bind_graph(tr, plan)
        built["symbol"] = out_sym  # hold the graph alive (id-keyed cache)
        self._built = built
        return built

    def _check_trainer(self, tr) -> None:
        from ..optimizer import FusedUpdater
        if tr._update_on_kvstore:
            raise _Ineligible("update_on_kvstore trainers push per key "
                              "through the kvstore")
        if not tr._fused:
            raise _Ineligible("MXNET_FUSED_TRAINER=0 pins the legacy path")
        upd = tr._updaters[0]
        if not isinstance(upd, FusedUpdater) or \
                not getattr(upd.optimizer, "fused", False):
            raise _Ineligible(
                f"optimizer {type(upd.optimizer).__name__} has no "
                "fused_step")
        if tr._kv is not None and tr._kv.num_workers > 1:
            raise _Ineligible("multi-host kvstore collectives are not "
                              "jit-inlinable yet")
        for p in tr._params:
            st = getattr(p, "_grad_stype", "default")
            if st not in ("default", "row_sparse"):
                raise _Ineligible(f"grad_stype={st!r} parameter {p.name}")
            # row_sparse params are eligible (ISSUE 20) but validate
            # against the traced graph in _bind_graph: the weight must
            # be a pure sparse_grad Embedding table fed ids straight
            # from the data input, with row-gatherable optimizer state
            if p.grad_req not in ("write", "null"):
                raise _Ineligible(
                    f"grad_req={p.grad_req!r} on {p.name} (vjp gives "
                    "write semantics)")
            if p.grad_req != "null" and len(p.list_data()) != 1:
                raise _Ineligible(f"multi-device copies of {p.name}")

    def _trace_graph(self):
        """Compose net + loss symbolically into one GraphPlan (the same
        machinery hybridize() uses, extended through the loss)."""
        dsym = sym_mod.Variable(_DATA)
        lsym = sym_mod.Variable(_LABEL)
        out = self.net(dsym)
        if isinstance(out, (list, tuple)):
            if len(out) != 1:
                raise _Ineligible("multi-output networks")
            out = out[0]
        loss_sym = self.loss_fn(out, lsym)
        if isinstance(loss_sym, (list, tuple)):
            if len(loss_sym) != 1:
                raise _Ineligible("multi-output losses")
            loss_sym = loss_sym[0]
        plan = GraphPlan(loss_sym)
        for s in plan.steps:
            if s.op.name == "Custom" or not s.op.differentiable:
                raise _Ineligible(
                    f"op {s.op.name} is not whole-step traceable")
        return plan, loss_sym

    def _bind_graph(self, tr, plan):
        """Map graph inputs onto trainer parameters; freeze the live
        order, bucket layout, and updater keys the program will use —
        all IDENTICAL to the fused path's so optimizer/residual state is
        interchangeable between the two."""
        params_by_name = {p.name: p for p in tr._params}
        gset, cnames = set(), []
        for n in plan.arg_names:
            if n in (_DATA, _LABEL):
                continue
            p = params_by_name.get(n)
            if p is None:
                raise _Ineligible(
                    f"graph input {n!r} is not a trainer parameter")
            (gset.add(n) if p.grad_req != "null" else cnames.append(n))
        for n in plan.aux_names:
            if n not in params_by_name:
                raise _Ineligible(
                    f"auxiliary state {n!r} is not a trainer parameter")
        if not gset:
            raise _Ineligible("no trainable parameters in the graph")
        # live order = trainer param order, exactly like Trainer._step
        live = [(i, p) for i, p in enumerate(tr._params)
                if p.grad_req != "null"]
        missing = [p.name for _, p in live if p.name not in gset]
        if missing:
            raise _Ineligible(
                f"trainable parameters unused by the graph: {missing[:3]}"
                " (their gradients would go stale)")
        idx = tuple(i for i, _ in live)
        gnames = [p.name for _, p in live]
        sig = tuple((tuple(p.data().shape), str(p.data().dtype))
                    for _, p in live)
        # sparse-embedding params (ISSUE 20): a row-sparse grad is
        # whole-step eligible only when the traced graph proves the
        # rows-only rewrite is exact — the weight feeds nothing but ONE
        # sparse_grad Embedding step whose ids come straight from the
        # data input (so the in-program unique/scatter sees every
        # touched row)
        sga = plan.sparse_grad_args()
        embed = {}
        for _, p in live:
            if getattr(p, "_grad_stype", "default") == "default":
                continue
            uses = sga.get(p.name)
            if not uses:
                raise _Ineligible(
                    f"row-sparse parameter {p.name} is not a pure "
                    "sparse_grad Embedding weight")
            if len(uses) != 1 or uses[0][1] != _DATA:
                raise _Ineligible(
                    f"sparse embedding {p.name} must be looked up exactly "
                    "once, with ids straight from the data input")
            # shape[0]/step index are host ints already — no device read
            embed[p.name] = {"step": uses[0][0],
                             "vocab": p.data().shape[0]}
        # the bucketer (and so compression residuals) covers DENSE
        # params only — row-sparse grads never flatten into buckets, on
        # this path or the trainer's fused path, so residual layouts
        # stay interchangeable between the two
        dlive = [(i, p) for i, p in live if p.name not in embed]
        dsig = tuple((tuple(p.data().shape), str(p.data().dtype))
                     for _, p in dlive)
        didx = tuple(i for i, _ in dlive)
        bk = tr._ensure_bucketer(dsig, didx) if dlive else None
        upd = tr._updaters[0]
        if self.mesh is not None:
            # annotate BEFORE the updater seeds optimizer state: the
            # zeros_like slots inherit each param's committed
            # NamedSharding, so momentum/adam state shards exactly like
            # its weight.  Trainable >=2-D tensors take the model-axis
            # default unless the user pinned a spec via set_sharding;
            # consts and aux (BN running stats) replicate — XLA then
            # inserts whatever collectives the annotated dataflow needs.
            from ..parallel import mesh as _pmesh
            from jax.sharding import PartitionSpec as _P
            for _, p in live:
                spec = p.sharding_spec
                if spec is None:
                    # a parameter may pin its own layout rule (the
                    # sharded-embedding row partition along
                    # MXNET_EMBED_SHARD_AXIS) ahead of the generic
                    # largest-dim default
                    hint = getattr(p, "_spec_hint", None)
                    spec = hint(self.mesh) if hint is not None else \
                        _pmesh.default_param_spec(
                            self.mesh, tuple(p.data().shape))
                p.set_sharding(self.mesh, spec)
            for n in itertools.chain(cnames, plan.aux_names):
                p = params_by_name[n]
                spec = p.sharding_spec
                p.set_sharding(self.mesh,
                               spec if spec is not None else _P())
        for i, p in live:
            upd._ensure_state(i, p.data())
            if self.mesh is not None:
                # states may predate the sharding (e.g. the first step
                # fell back on DeferredInitializationError and the fused
                # path seeded them on one device) — conform them to the
                # weight's committed NamedSharding so the donated program
                # sees one placement
                from ..optimizer import _conform_state_sharding
                upd.states[i] = _conform_state_sharding(
                    upd.states[i], p.data())
            if p.name in embed and not upd._rowable_state(
                    upd.states[i], p.data().shape[0]):
                raise _Ineligible(
                    f"optimizer state for embedding {p.name} is not "
                    "row-gatherable (leaves must be table-shaped or "
                    "None)")
        return {"plan": plan, "idx": idx, "gnames": gnames,
                "cnames": tuple(cnames),
                "aux_names": tuple(plan.aux_names),
                "params": params_by_name, "bk": bk, "sig": sig,
                "embed": embed, "uid": next(_PLAN_UID)}

    # -- the compiled program ------------------------------------------------
    def _make_ftrain(self, built, opt_, policy, thr, window):
        """The raw (un-jitted) whole-step function:

        ftrain(gparams, states, residuals, scaler, aux, consts, data,
               label, key, lrs, wds, ts)
          -> (loss, new_aux, new_params, new_states, new_residuals,
              new_scaler, new_ts)

        ``_build_fn`` jits it with donation for the 1-dispatch step;
        ``autotune.SuperStepCompiler`` wraps the SAME function in a
        ``lax.scan`` over K batches (the scan body must be the exact op
        sequence of one whole step — the superstep/whole-step bitwise
        parity contract hangs on sharing this tracer)."""
        plan = built["plan"]
        gnames = built["gnames"]
        idx = built["idx"]
        bk = built["bk"]
        embed = built.get("embed") or {}
        dnames = [n for n in gnames if n not in embed]
        lp = _LP_DTYPES.get(policy)
        overrides = _amp_overrides(plan, lp) if lp is not None else None
        use_comp = thr is not None and bk is not None and bool(dnames)
        use_scaler = policy == "fp16"
        flatten_inline = bk.flatten_inline if use_comp else None
        unflatten_inline = bk.unflatten_inline if use_comp else None
        if use_comp:
            from ..kvstore import reduce_buckets_inline
        fused_step = opt_._fused_step_mp

        def ftrain(gparams, states, residuals, scaler, aux, consts,
                   data, label, key, lrs, wds, ts):
            # -- sparse-embedding pre-pass (ISSUE 20): batch ids ->
            # shared sorted-unique rows.  jnp.unique pads its static
            # output with fill_value=vocab — a POSITIVELY out-of-range
            # sentinel every mode="drop" scatter below discards (never
            # -1, which .at[] would wrap onto the last real row).
            elook = {}
            for n, info in embed.items():
                vocab = info["vocab"]
                ids = jnp.clip(data.astype(jnp.int32), 0,
                               vocab - 1).ravel()
                uids, uinv = jnp.unique(ids, size=ids.shape[0],
                                        fill_value=vocab,
                                        return_inverse=True)
                elook[n] = (uids, jnp.ravel(uinv))
            # one zero dummy per embedding, shaped like the lookup
            # OUTPUT (tokens x dim, not vocab x dim) — the executor's
            # rows-only rewrite idiom: differentiating the dummy yields
            # the per-token cotangent rows, so the table's O(vocab)
            # dense cotangent never materializes in the program
            dums = {n: jnp.zeros(tuple(data.shape)
                                 + tuple(gparams[n].shape[1:]),
                                 gparams[n].dtype) for n in embed}
            dparams = {n: gparams[n] for n in dnames}

            def fwd(p, dm):
                m = dict(consts)
                m[_DATA] = data
                m[_LABEL] = label
                m.update(p)
                ov = dict(overrides) if overrides else {}
                for n, info in embed.items():
                    # the weight var must still resolve (plan.run binds
                    # every in_ref before consulting overrides), but it
                    # is NOT a vjp primal — its gradient flows through
                    # the dummy instead
                    m[n] = gparams[n]

                    def _lookup(params, ins, _n=n):
                        vsz = ins[1].shape[0]
                        iid = jnp.clip(ins[0].astype(jnp.int32), 0,
                                       vsz - 1)
                        return (jnp.take(jax.lax.stop_gradient(ins[1]),
                                         iid, axis=0) + dm[_n],)

                    ov[info["step"]] = _lookup
                outs, new_aux = plan.run(m, aux, key, True,
                                         step_overrides=ov or None)
                total = jnp.sum(outs[0].astype(jnp.float32))
                if use_scaler:
                    total = total * scaler["scale"]
                return total, (outs[0], new_aux)

            _, vjp_fn, (loss, new_aux) = jax.vjp(fwd, dparams, dums,
                                                 has_aux=True)
            gd, gdum = vjp_fn(jnp.asarray(1.0, jnp.float32))
            glist = [gd[n] for n in dnames]
            # segment-sum the per-token rows onto the unique ids — the
            # same unique + .at[inv].add the eager rsp deposit
            # (_dedup_rows) runs, so the two paths' row grads match
            # bitwise in f32
            egrads = {}
            for n in embed:
                uids, uinv = elook[n]
                rows = gdum[n].reshape((uinv.shape[0],)
                                       + tuple(gparams[n].shape[1:]))
                egrads[n] = jnp.zeros(rows.shape, rows.dtype) \
                    .at[uinv].add(rows)
            finite = None
            if use_scaler:
                inv = 1.0 / scaler["scale"]
                glist = [(g.astype(jnp.float32) * inv).astype(g.dtype)
                         for g in glist]
                egrads = {n: (g.astype(jnp.float32) * inv)
                          .astype(g.dtype) for n, g in egrads.items()}
                finite = jnp.asarray(True)
                for g in itertools.chain(glist, egrads.values()):
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
            new_res = residuals
            if use_comp:
                # literal named scopes over the non-graph step stages:
                # HLO metadata then attributes the bucketed reduce and
                # the fused optimizer math to their own per_layer()
                # rows, next to the graph nodes' layer scopes.  The
                # buckets hold DENSE grads only — row-sparse grads stay
                # rows-only and never compress
                with _introspect.layer_scope("allreduce"):
                    flats = flatten_inline(glist)
                    red, new_res, _errs = reduce_buckets_inline(
                        flats, residuals, thr)
                    glist = unflatten_inline(red)
            with _introspect.layer_scope("optimizer"):
                new_p, new_s = {}, []
                di = 0
                for k, n in enumerate(gnames):
                    if n in embed:
                        # sparse leg: gather the touched rows (weight +
                        # lazy per-row optimizer state), step them, and
                        # scatter back IN PROGRAM — the table-shaped
                        # output aliases the donated input buffer, so
                        # the update is a true in-place scatter
                        # (audit_programs checks the alias survived)
                        uids, _ = elook[n]
                        wr = jnp.take(gparams[n], uids, axis=0,
                                      mode="clip")
                        srows = jax.tree_util.tree_map(
                            lambda s: jnp.take(s, uids, axis=0,
                                               mode="clip"), states[k])
                        nwr, nsr = fused_step(idx[k], wr, egrads[n],
                                              srows, lrs[k], wds[k],
                                              ts[k])
                        new_p[n] = gparams[n].at[uids].set(
                            _cast_like(nwr, wr), mode="drop")
                        new_s.append(jax.tree_util.tree_map(
                            lambda s, r: s.at[uids].set(
                                _cast_like(r, s), mode="drop"),
                            states[k], nsr))
                        continue
                    nw, ns = fused_step(idx[k], gparams[n], glist[di],
                                        states[k], lrs[k], wds[k], ts[k])
                    di += 1
                    new_p[n] = _cast_like(nw, gparams[n])
                    new_s.append(_cast_like(ns, states[k]))
            new_scaler = scaler
            if use_scaler:
                # skip-step: a nonfinite gradient anywhere keeps params,
                # states, residuals, aux (BN running stats — an
                # overflowing batch must not poison them forever), and
                # the bias-correction counter at their pre-step values —
                # only the scaler moves (backoff)
                new_aux = {n: jnp.where(finite, a, aux[n])
                           for n, a in new_aux.items()}
                new_p = {n: jnp.where(finite, new_p[n], gparams[n])
                         for n in gnames}
                new_s = [_sel(finite, a, b) for a, b in zip(new_s, states)]
                if use_comp:
                    new_res = [jnp.where(finite, a, b)
                               for a, b in zip(new_res, residuals)]
                nts = jnp.where(finite, ts + 1, ts)
                good = jnp.where(finite, scaler["good"] + 1, 0)
                grow = good >= window
                scale = jnp.where(grow,
                                  jnp.minimum(scaler["scale"]
                                              * _SCALE_GROWTH,
                                              _SCALE_MAX),
                                  scaler["scale"])
                scale = jnp.where(finite, scale,
                                  jnp.maximum(scaler["scale"]
                                              * _SCALE_BACKOFF, 1.0))
                good = jnp.where(grow, jnp.zeros_like(good), good)
                new_scaler = {"scale": scale, "good": good}
            else:
                nts = ts + 1
            return loss, new_aux, new_p, new_s, new_res, new_scaler, nts

        return ftrain

    def _build_fn(self, built, opt_, policy, thr, window):
        """One donated jitted whole-step program: gparams/states/
        residuals/scaler/aux are DONATED — the step updates the model
        truly in place on backends with donation."""
        ftrain = self._make_ftrain(built, opt_, policy, thr, window)
        mesh = self.mesh
        if mesh is None or mesh.size <= 1:
            return jax.jit(ftrain, donate_argnums=(0, 1, 2, 3, 4))
        # GSPMD propagation is free to pick DIFFERENT shardings for the
        # updated params/states than their inputs carry — and a donated
        # buffer whose output layout differs cannot alias (donation
        # silently degrades to a copy + reshard).  Pin every donated
        # output to its input's committed NamedSharding so the alias
        # table stays complete; same-shape state leaves take their
        # weight's sharding (momentum/adam moments shard like the
        # weight), everything else replicates.
        from jax.lax import with_sharding_constraint as _wsc
        from jax.sharding import NamedSharding, PartitionSpec
        params = built["params"]
        gnames = built["gnames"]
        psh = {n: params[n].sharding for n in gnames}
        repl = NamedSharding(mesh, PartitionSpec())

        def _pin_state(s, wsh, wshape):
            if s is None:
                return None
            if isinstance(s, (tuple, list)):
                return type(s)(_pin_state(x, wsh, wshape) for x in s)
            tgt = wsh if tuple(s.shape) == wshape and wsh is not None \
                else repl
            return _wsc(s, tgt)

        def fshard(gparams, states, residuals, scaler, aux, consts,
                   data, label, key, lrs, wds, ts):
            (loss, new_aux, new_p, new_s, new_res, new_scaler,
             nts) = ftrain(gparams, states, residuals, scaler, aux,
                           consts, data, label, key, lrs, wds, ts)
            new_p = {n: _wsc(v, psh[n] if psh[n] is not None else repl)
                     for n, v in new_p.items()}
            new_s = [_pin_state(s, psh[gnames[k]],
                                tuple(gparams[gnames[k]].shape))
                     for k, s in enumerate(new_s)]
            new_aux = {n: _wsc(v, repl) for n, v in new_aux.items()}
            new_scaler = {n: _wsc(v, repl)
                          for n, v in new_scaler.items()} \
                if isinstance(new_scaler, dict) else new_scaler
            return (loss, new_aux, new_p, new_s, new_res, new_scaler,
                    nts)

        return jax.jit(fshard, donate_argnums=(0, 1, 2, 3, 4))

    # -- per-step driver -----------------------------------------------------
    def _hyper_arrays(self, opt_, idx):
        """Device-cached lr/wd vectors + the device-resident step
        counter — ``optimizer.HyperDeviceCache``, the same
        implementation ``FusedUpdater.hyper_arrays`` uses (under fp16
        the counter advances only on applied steps).  A checkpointed
        APPLIED-step vector takes re-seed precedence: the schedule
        counts include skipped steps, so reseeding Adam's
        bias-correction t from them would diverge from the
        uninterrupted run after any skip."""
        def _pending():
            pend = getattr(self.trainer, "_applied_ts_pending", None)
            if pend is not None and pend[0] == idx:
                # consumed only when a (re)seed actually happens —
                # HyperDeviceCache calls this inside its reseed branch
                self.trainer._applied_ts_pending = None
                return pend[1]
            return None

        return self._hyper_cache.arrays(opt_, idx, pending_ts=_pending)

    def _run(self, built, data, label, bs, policy):
        tr = self.trainer
        # chaos site, fired BEFORE the schedule counters advance and
        # before any donated buffer is touched: an injected raise is a
        # cleanly-retryable failed step (the fused path fires the same
        # site in Trainer._step — exactly one per training step)
        _fi_fire("trainer.step", step=tr._step_id)
        upd = tr._updaters[0]
        opt_ = upd.optimizer
        idx = built["idx"]
        if policy != "f32" and any(d != "float32" for _, d in built["sig"]):
            raise _AmpIneligible(
                f"MXNET_AMP={policy} needs float32 master weights")
        gc = getattr(tr._kv, "_gc", None) if tr._kv is not None else None
        thr = gc.threshold if gc is not None else None
        if thr is not None and self.mesh is not None \
                and self.mesh.size > 1:
            # GSPMD supersedes the explicit 2-bit bucketed allreduce on
            # a real mesh: jit inserts the cross-shard collectives
            # itself, so compressing an in-program reduce that no
            # longer carries the cross-device traffic would change
            # numerics for nothing.  A 1-chip mesh keeps compression —
            # the bitwise-parity pin vs the replicated path covers it.
            if not self._mesh_comp_warned:
                self._mesh_comp_warned = True
                from ..parallel.mesh import mesh_signature
                logger.warning(
                    "2-bit gradient compression is disabled inside the "
                    "whole-step program on a multi-device mesh (%s) — "
                    "GSPMD collectives replace the bucketed allreduce",
                    mesh_signature(self.mesh))
            thr = None
        if built["bk"] is None:
            # every trainable param is a sparse embedding (ISSUE 20):
            # no dense buckets exist for compression to act on
            thr = None
        residuals = []
        if thr is not None:
            if tr._residuals is None:
                tr._residuals = tr._init_residuals(built["bk"])
            residuals = tr._residuals
        scaler = {}
        window = 0
        if policy == "fp16":
            st = tr._ensure_scaler()
            window = st["window"]  # a python int, set at creation
            scaler = {"scale": st["scale"], "good": st["good"]}

        opt_.rescale_grad = tr._scale / bs
        # snapshot the schedule counters: the program traces lazily on
        # its first call below, and a trace-time failure routes step()
        # to the fallback path whose Trainer.step counts the SAME step
        # again — without rollback num_update would be off by one
        # forever (lr schedules, Adam bias correction)
        prev_nu = opt_.num_update
        prev_counts = {i: opt_._index_update_count.get(i) for i in idx}
        for i in idx:
            opt_._update_count(i)
        try:
            return self._dispatch(built, opt_, upd, policy, thr, window,
                                  scaler, residuals, data, label, bs)
        except Exception:
            opt_.num_update = prev_nu
            for i, c in prev_counts.items():
                if c is None:
                    opt_._index_update_count.pop(i, None)
                else:
                    opt_._index_update_count[i] = c
            raise

    def _dispatch(self, built, opt_, upd, policy, thr, window, scaler,
                  residuals, data, label, bs):
        tr = self.trainer
        params = built["params"]
        gnames = built["gnames"]
        idx = built["idx"]
        mesh = self.mesh
        data_j, label_j = data._data, label._data
        if mesh is not None:
            from ..parallel import mesh as _pmesh
            daxis = _pmesh.data_axis(mesh)
            dsize = int(mesh.shape[daxis])
            if int(data.shape[0]) % dsize != 0:
                raise _ShardIneligible(
                    f"batch of {int(data.shape[0])} does not divide "
                    f"the mesh's {daxis} axis (size {dsize})")
            # committed batch placement: jit reads in_shardings off
            # these arrays and compiles the sharded program.  A raw
            # placement the runtime folds into the dispatch, not a
            # tracked host transfer — the 1-dispatch gate stands.
            bsh = _pmesh.batch_sharding(mesh)
            data_j = jax.device_put(data_j, bsh)  # graft-lint: disable=memory-hygiene
            label_j = jax.device_put(label_j, bsh)  # graft-lint: disable=memory-hygiene
        lrs, wds, ts, counts_t = self._hyper_arrays(opt_, idx)
        gparams = {n: params[n].list_data()[0]._data for n in gnames}
        consts = {n: params[n].list_data()[0]._data
                  for n in built["cnames"]}
        aux = {n: params[n].list_data()[0]._data
               for n in built["aux_names"]}
        if mesh is not None and mesh.size > 1:
            # a supervisor/checkpoint restore (set_states_bytes)
            # rehydrates optimizer state on the default device while
            # _load_init re-commits the weights to their NamedSharding
            # — conform the states back to their weights' placement
            # (device_put is an identity when already placed)
            from ..optimizer import _conform_state_sharding
            for j, n in enumerate(gnames):
                upd.states[idx[j]] = _conform_state_sharding(
                    upd.states[idx[j]], params[n].list_data()[0])
        svals = [upd._state_data(upd.states[i]) for i in idx]

        upd.dtype_policy = policy
        # the key's policy component carries EVERYTHING policy-derived
        # (fp16 folds the loss-scale window in): lookup_program's loud
        # recompile detection compares the policy-independent tail, so a
        # policy-derived field there would mask e.g. the f32->fp16 flip
        pol_key = policy if policy != "fp16" else f"fp16/w{window}"
        from ..parallel.mesh import mesh_signature as _mesh_sig
        msig = _mesh_sig(mesh)
        key = ("whole_step", pol_key, type(opt_).__name__,
               opt_.fused_hyper_key(), idx,
               tuple(d for _, d in built["sig"]),
               built["uid"], thr,
               built["bk"].sizes if thr is not None else None,
               jax.tree_util.tree_structure(svals), msig)
        fn = upd.lookup_program(
            key, lambda: self._build_fn(built, opt_, policy, thr,
                                        window))
        note_key = (key, tuple(data.shape), tuple(label.shape))
        if _introspect.ENABLED and note_key not in self._noted_keys:
            # once per program cache key, BEFORE the donated dispatch
            # (every argument is still live): capture the whole-step
            # program's analytical flops/bytes — the MFU numerator and
            # the per_layer() subject.  A retrace only (no XLA compile
            # unless MXNET_INTROSPECT_HLO=1), no dispatch, so the
            # 1-dispatch perf_smoke gate is unaffected.  The signature
            # keys the perf-regression baseline per (model, optimizer,
            # precision, batch shape) on this platform; a new data
            # shape re-notes (jax retraces per shape anyway), keeping
            # the recorded flops honest for the running batch size.
            self._noted_keys.add(note_key)
            import hashlib
            # data/label shapes fold into the signature: step time
            # scales with batch size, so a legitimate bs change must
            # select a DIFFERENT perf baseline file, not fire a false
            # regression against the old batch's numbers
            # mesh_signature folds in too: the perf sentinel then keys
            # its baseline per mesh SHAPE — a resharded run measures
            # against its own history, not the replicated path's
            sig = hashlib.sha1(repr(
                (built["sig"], type(opt_).__name__, policy,
                 thr is not None, tuple(data.shape),
                 tuple(label.shape), msig)).encode()).hexdigest()[:16]
            # the program CONTRACT the post-compile auditor
            # (analysis.audit_programs, ISSUE 15) verifies against the
            # lowered HLO: every donated leaf must become an
            # input-output alias, AMP must leave no f32 dot/conv, a
            # whole-step program contains zero host callbacks (Custom
            # ops are ineligible by construction), and the collective
            # story matches the mesh — zero collectives replicated
            # (single-process inline bucketed reduce; multi-host
            # kvstore is ineligible), or the per-axis GSPMD plan on a
            # multi-device mesh
            contracts = {
                "donate_argnums": (0, 1, 2, 3, 4),
                "donated_leaves": len(jax.tree_util.tree_leaves(
                    (gparams, svals, residuals, scaler, aux))),
                "amp": policy,
                "host_callbacks": 0,
                "buckets": len(built["bk"].sizes)
                if thr is not None else 0,
            }
            if mesh is not None and mesh.size > 1:
                # the GSPMD collective plan the auditor verifies
                # against the sharded HLO: every mesh axis of size > 1
                # must carry at least one XLA-inserted collective
                # (gradient reduce over batch, partial-sum reduce over
                # model) — and donation must STILL alias under sharding
                contracts["mesh_axes"] = {
                    a: int(mesh.shape[a]) for a in mesh.axis_names}
                contracts["collective_plan"] = {
                    a: 1 for a in mesh.axis_names
                    if int(mesh.shape[a]) > 1}
            else:
                # single-process inline bucketed reduce (multi-host
                # kvstore is ineligible): zero collective ops
                contracts["collectives"] = 0
            _introspect.note_jit(
                "whole_step", fn, gparams, svals, residuals, scaler, aux,
                consts, data_j, label_j,
                jax.random.PRNGKey(0), lrs, wds, ts, signature=sig,
                contracts=contracts)

        # chaos site for transient device loss at the dispatch boundary:
        # fires before fn() executes, so the donated buffers are still
        # live and a supervisor restore+retry reuses the built program
        _fi_fire("device.unavailable", step=tr._step_id)
        from .. import random as _random
        rkey = _random.next_key()
        on = _metrics.ENABLED
        d0 = _metrics.step_dispatches() if on else 0.0
        if on:
            _metrics.XLA_LAUNCHES.inc(kind="whole_step")
            _metrics.OPTIMIZER_STEPS.inc()
        try:
            with trace_span("whole_step", cat="trainer"), \
                    _flight.phase_span("whole_step", cat="step",
                                       step=tr._step_id, watch=True,
                                       mem=True), \
                    _memory.oom_guard("wholestep.step"):
                loss, new_aux, new_p, new_s, new_res, new_scaler, nts = \
                    fn(gparams, svals, residuals, scaler, aux, consts,
                       data_j, label_j, rkey, lrs, wds, ts)
        except BaseException:
            # MXNET_SANITIZE runtime twin of the use-after-donate
            # static rule: an exception out of the donated program
            # means the params/states/aux buffers may already be
            # consumed by XLA.  Poison their wrappers so any touch
            # before a restore raises a typed DonatedBufferError
            # (naming this dispatch) instead of jax's opaque
            # deleted-array error; the supervisor's snapshot restore
            # (_load_init / set_states_bytes) replaces _data and
            # thereby clears the poison.  One boolean test when the
            # sanitizer is off.
            if _san.ENABLED:
                _san.poison_donated(
                    "whole_step",
                    *[params[n].list_data() for n in gnames],
                    *[params[n].list_data()
                      for n in built["aux_names"]],
                    *[upd.states[i] for i in idx])
            raise
        tr._step_id += 1
        if on:
            _metrics.TRAINER_STEP_DISPATCHES.set(
                _metrics.step_dispatches() - d0)
        if _introspect.ENABLED:
            # perf-regression sentinel heartbeat: one counter bump per
            # step; every SENTINEL_EVERY steps the warmed whole_step
            # EWMA compares against the persisted baseline
            _introspect.sentinel_tick("whole_step")
        if _journal.ENABLED:
            _journal.maybe_milestone(tr._step_id, source="whole_step")

        self._commit_outputs(built, upd, policy, thr, new_p, new_aux,
                             new_s, new_res, new_scaler, nts, counts_t)
        self._ran = True
        return NDArray(loss, data.context)

    def _commit_outputs(self, built, upd, policy, thr, new_p, new_aux,
                        new_s, new_res, new_scaler, nts, counts_t):
        """Write the program's functional outputs back onto the live
        model/trainer — shared verbatim by the whole-step dispatch and
        the superstep's scan dispatch (K fused steps commit exactly
        like one)."""
        tr = self.trainer
        params = built["params"]
        idx = built["idx"]
        for n in built["gnames"]:
            params[n].list_data()[0]._set_data(new_p[n])
        for n in built["aux_names"]:
            params[n].list_data()[0]._set_data(new_aux[n])
        for k, i in enumerate(idx):
            upd.states[i] = upd._state_writeback(upd.states[i], new_s[k])
        if thr is not None:
            # the program returns FRESH residual arrays (functional
            # update) — re-register so ledger attribution follows the
            # live ones, same as the fused allreduce does
            if _memory.ENABLED:
                tr._residuals = [_memory.register(
                    r, tag="compression_residual") for r in new_res]
            else:
                tr._residuals = list(new_res)
        if policy == "fp16":
            st = tr._scaler
            st["scale"], st["good"] = new_scaler["scale"], \
                new_scaler["good"]
        self._hyper_cache.commit(idx, nts, counts_t)
        # mirror the device-side applied-step vector onto the trainer so
        # save_states can persist it with the scaler (fp16 kill-resume:
        # ts lags the schedule counts by one per skipped step)
        tr._applied_ts = (idx, nts)
