"""gluon.Trainer (parity: python/mxnet/gluon/trainer.py:27,108-127,156).

Applies an Optimizer to a ParameterDict; kvstore-backed when requested so
`KVStore('tpu_sync')` data parallelism works unmodified from gluon code.

TPU fast path (MXNET_FUSED_TRAINER, default on): a steady-state `step` on a
dense model is O(1) XLA dispatches regardless of parameter count —
  1. bucketed allreduce: all dense grads flatten into size-capped buckets
     (MXNET_BUCKET_SIZE_MB, ~32MB) in ONE jitted program and reduce via
     one store-less `kvstore.allreduce` over the transient buckets;
  2. fused update: `FusedUpdater.update_all` slices each gradient straight
     out of the reduced flat buckets inside its single compiled optimizer
     program (grad_views), so un-flattening costs nothing.
`compression_params={'type': '2bit'}` composes with the fast path: the
buckets quantize against flat per-bucket error-feedback residuals (one
more fused program; the dist leg ships the packed 4-codes/byte payload,
~1/16 of the float32 bytes) while per-parameter residual semantics stay
identical to the reference's per-key quantizer — see
kvstore._compressed_allreduce_impl.
`MXNET_FUSED_TRAINER=0` pins the reference-shaped legacy path (per-key
push/pull loop + per-parameter updater calls) for A/B and bisection.
"""
from __future__ import annotations

import os as _os
import pickle

import jax.numpy as jnp
import numpy as _np

from ..analysis import hot_path
from ..base import MXNetError, getenv
from ..faultinject import fire as _fi_fire
from ..ndarray import NDArray
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability import introspect as _introspect
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from ..observability.tracing import trace_span
from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 mesh=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._params.append(param)
        self._compression_params = compression_params
        # GSPMD mesh this trainer's params shard over (ISSUE 18): the
        # whole-step/superstep compilers resolve explicit arg > this >
        # the ambient parallel.mesh.current_mesh(); None = replicated
        self._mesh = mesh
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore
        self._update_on_kvstore_arg = update_on_kvstore
        self._fused = bool(getenv("MXNET_FUSED_TRAINER", True))
        self._bucketer = None
        self._bucket_sig = None
        # (flat bucket arrays, per-param views, index tuple) staged by a
        # for-step allreduce for the fused update to consume
        self._reduced = None
        # {param_idx: merged RowSparseNDArray} staged by a for-step
        # allreduce_rowsparse for the fused sparse update (ISSUE 20)
        self._reduced_rsp = None
        # (key, (live, rsp, rsp_idx, dense)) — see _live_split
        self._live_split_cache = None
        # 2-bit error-feedback state for the compressed bucketed
        # allreduce: one flat f32 residual per bucket, laid out by the
        # bucketer (each parameter's residual is its own slice, so
        # per-parameter error-feedback semantics survive bucketing);
        # rebuilt zero-initialized on bucket-signature change
        self._residuals = None
        # (bucket_sig, numpy arrays) from load_states, adopted — with a
        # signature check — when the bucketer is next built
        self._pending_residuals = None
        # dynamic loss-scaling state for MXNET_AMP=fp16 whole-step
        # training (gluon/wholestep.py): device scalars donated into the
        # compiled step each call; rides save_states/load_states so a
        # resumed run continues the same scale trajectory
        self._scaler = None
        # (idx, device applied-step vector) mirrored by the whole-step
        # compiler after each step; persisted with the scaler because
        # fp16 skip-steps make it lag the schedule counts — a resume
        # seeding Adam's bias-correction t from the counts would diverge
        self._applied_ts = None
        self._applied_ts_pending = None  # set by load_states, consumed once
        # monotonically increasing step id stamped on flight-recorder
        # phase records (joins allreduce/compress/update sub-phases to
        # their step in a timeline dump)
        self._step_id = 0

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        arg_arrays = {param.name: param.data() for param in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore, 1, arg_arrays)
        if self._update_on_kvstore_arg is not None:
            # explicit user override (parity: later-1.x Trainer arg)
            update_on_kvstore = bool(self._update_on_kvstore_arg)
            if update_on_kvstore and kvstore is None:
                # parity: reference Trainer raises rather than silently
                # training with local updaters (save_states would then
                # write a different state format than the user asked for)
                raise ValueError(
                    "update_on_kvstore=True requires a kvstore, but "
                    f"kvstore={self._kvstore!r} resolved to none — set "
                    "update_on_kvstore=False or pass a kvstore")
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv = kvstore
        self._update_on_kvstore = update_on_kvstore and kvstore is not None
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler else self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- stale-grad accounting ----------------------------------------------
    @staticmethod
    def _is_fresh(param):
        return param.fresh_grad

    def _mask_stale(self, live, ignore_stale_grad):
        """Parity: gluon/trainer.py:216 — a gradient that backward has not
        rewritten since the last step either raises (default) or masks its
        parameter out of the update (ignore_stale_grad=True)."""
        if ignore_stale_grad:
            return [(i, p) for i, p in live if self._is_fresh(p)]
        for i, p in live:
            for d in p.list_data():
                if not getattr(d, "_fresh_grad", False):
                    raise UserWarning(
                        f"Gradient of Parameter `{p.name}` on context "
                        f"{d.context} has not been updated by backward "
                        "since last `step`. This could mean a bug in your "
                        "model that made it only use a subset of the "
                        "Parameters (Blocks) for this iteration. If you "
                        "are intentionally only using a subset, call step "
                        "with ignore_stale_grad=True to suppress this "
                        "warning and skip updating of Parameters with "
                        "stale gradient")
        return live

    @staticmethod
    def _clear_fresh(entries):
        for _, p in entries:
            for d in p.list_data():
                d._fresh_grad = False

    def _live_split(self):
        """Cached dense/row-sparse split of the live params (ISSUE 20):
        ``(live, rsp, rsp_idx, dense)``.  The per-step linear
        ``getattr`` scans collapse to one build per param-set change —
        keyed on param identity + grad_req + grad_stype, the same
        identity discipline as the bucketer signature (PR 3)."""
        key = tuple((id(p), p.grad_req,
                     getattr(p, "_grad_stype", "default"))
                    for p in self._params)
        cached = self._live_split_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        rsp = [(i, p) for i, p in live
               if getattr(p, "_grad_stype", "default") == "row_sparse"]
        rsp_idx = frozenset(i for i, _ in rsp)
        dense = [ip for ip in live if ip[0] not in rsp_idx]
        out = (live, rsp, rsp_idx, dense)
        self._live_split_cache = (key, out)
        return out

    @hot_path
    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with grads scaled by 1/batch_size.

        TPU hot path: all parameters update in O(1) XLA dispatches via
        bucketed KVStore.pushpull + FusedUpdater.update_all (replaces the
        reference's per-parameter kvstore push loop, gluon/trainer.py:191-226).
        The per-step dispatch delta is published as the
        mxnet_trainer_step_dispatches gauge."""
        on = _metrics.ENABLED
        d0 = _metrics.step_dispatches() if on else 0.0
        with trace_span("trainer_step", cat="optimizer"), \
                _flight.phase_span("trainer_step", cat="step",
                                   step=self._step_id, watch=True,
                                   mem=True):
            self._step(batch_size, ignore_stale_grad)
        self._step_id += 1
        if on:
            _metrics.TRAINER_STEP_DISPATCHES.set(
                _metrics.step_dispatches() - d0)
        if _introspect.ENABLED:
            # perf-regression sentinel heartbeat for the fused path
            # (the whole-step path ticks its own phase in
            # WholeStepCompiler._dispatch): one counter bump per step
            _introspect.sentinel_tick("trainer_step")
        if _journal.ENABLED:
            _journal.maybe_milestone(self._step_id, source="trainer")

    def _step(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        # chaos site (one global read when no plan): fires BEFORE any
        # param/optimizer mutation, so an injected raise models a step
        # that failed without consuming state — the TrainingSupervisor
        # classifies it transient and retries (whole-step mode fires the
        # same site in WholeStepCompiler._run; exactly one per step)
        _fi_fire("trainer.step", step=self._step_id)
        self._optimizer.rescale_grad = self._scale / batch_size
        live, rsp, rsp_idx, dense = self._live_split()
        if self._kv is not None and self._update_on_kvstore:
            # parity: the reference NEVER masks the kvstore push set —
            # only the no-kvstore updater loop honors ignore_stale_grad.
            # Masking here would also desynchronize collective
            # participation across hosts (worker A skips a stale param
            # worker B pushes → mismatched allreduce → pod hang), so
            # stale grads raise (default) or push as-is.
            if not ignore_stale_grad:
                self._mask_stale(live, False)
            # row-sparse grad_stype params go through the kvstore per-key
            # sparse path (class-preserving push → lazy rsp optimizer on
            # the store) so untouched rows never decay
            if rsp:
                from ..ndarray import sparse as _sp
                for i, p in rsp:
                    # grads are already RowSparseNDArrays (rows-only
                    # autograd deposit); cast is only a legacy fallback
                    self._kv.pushpull(
                        i, [g if isinstance(g, _sp.RowSparseNDArray)
                            else _sp.cast_storage(g, "row_sparse")
                            for g in p.list_grad()],
                        out=p.list_data())
            if dense:
                if self._fused:
                    self._kv.pushpull([i for i, _ in dense],
                                      [p.list_grad() for _, p in dense],
                                      out=[p.list_data() for _, p in dense])
                else:
                    # MXNET_FUSED_TRAINER=0: the reference-shaped per-key
                    # loop, for A/B runs and bisection
                    for i, p in dense:
                        self._kv.pushpull(i, p.list_grad(),
                                          out=p.list_data())
            self._clear_fresh(live)
            return
        self._allreduce_grads(for_step=True)
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self, for_step=False):
        self._reduced = None
        self._reduced_rsp = None
        if self._kv is None:
            return
        live, rsp, rsp_idx, dense = self._live_split()
        if rsp:
            from ..ndarray import sparse as _sp
            fused_rsp = (for_step and self._fused
                         and not self._update_on_kvstore
                         and all(len(p.list_grad()) == 1 for _, p in rsp))
            if fused_rsp:
                # ONE row-sparse reduce over all sparse keys (ISSUE 20):
                # unique-concat + segment-sum, jit-inlinable — replaces
                # the per-key push/pull exile.  The merged grads are
                # staged for _update's fused sparse leg, consume-once.
                merged = self._kv.allreduce_rowsparse(
                    [[g if isinstance(g, _sp.RowSparseNDArray)
                       else _sp.cast_storage(g, "row_sparse")
                       for g in p.list_grad()] for _, p in rsp])
                self._reduced_rsp = {
                    i: m for (i, _), m in zip(rsp, merged)}
            else:
                for i, p in rsp:
                    # sparse keys keep the per-key class-preserving path
                    self._kv.push(i, p.list_grad())
                    if not self._update_on_kvstore:
                        self._kv.pull(i, p.list_grad())
        if not dense:
            return
        # 2-bit compression composes with bucketing: the quantizer is
        # purely elementwise, so flat per-bucket residuals (threaded
        # through _bucketed_pushpull) preserve per-parameter
        # error-feedback semantics exactly — fused-compressed matches
        # the legacy per-key-compressed path (tests/test_fused_step.py)
        fused_ok = (self._fused and not self._update_on_kvstore
                    and all(len(p.list_grad()) == 1 for _, p in dense))
        if not fused_ok:
            for i, param in dense:
                self._kv.push(i, param.list_grad())
                if not self._update_on_kvstore:
                    self._kv.pull(i, param.list_grad())
            return
        flats, views, idx = self._bucketed_pushpull(dense)
        if for_step:
            # the fused update slices grads straight out of the flat
            # buckets (grad_views); per-key grad buffers are rewritten
            # only for the public allreduce_grads() contract below
            self._reduced = (flats, views, idx)
        else:
            outs = self._bucketer.unflatten(flats)
            for (i, p), g in zip(dense, outs):
                p.list_grad()[0]._set_data(g)

    def _bucketed_pushpull(self, dense):
        """Flatten → one store-less fused allreduce over the buckets →
        reduced flat buckets.  Returns (flat arrays, per-param views,
        indices).  The buckets are TRANSIENT — they never enter the
        kvstore's backing store, so no gradient-sized copy is pinned and
        nothing is copied per step beyond the reduce itself."""
        grads = [p.list_grad()[0] for _, p in dense]
        sig = tuple((tuple(g.shape), str(g.dtype)) for g in grads)
        idx = tuple(i for i, _ in dense)
        bk = self._ensure_bucketer(sig, idx)
        gc = getattr(self._kv, "_gc", None)
        with trace_span("bucketed_allreduce", cat="kvstore"), \
                _flight.phase_span("allreduce", cat="kvstore",
                                   step=self._step_id, mem=True), \
                _memory.memory_scope("grad_bucket"):
            flats = bk.flatten([g.handle for g in grads])
            ctx = grads[0].context
            buckets = [NDArray(f, ctx) for f in flats]
            if gc is not None:
                if self._residuals is None:
                    self._residuals = self._init_residuals(bk)
                with _flight.phase_span("compress", cat="kvstore",
                                        step=self._step_id):
                    reduced, self._residuals = self._kv.allreduce(
                        buckets, compression=gc,
                        residuals=self._residuals)
                if _memory.ENABLED:
                    # the allreduce returns FRESH residual arrays each
                    # step (functional update) — re-register so the
                    # ledger keeps attributing the live ones
                    for r in self._residuals:
                        _memory.register(r, tag="compression_residual")
            else:
                reduced = self._kv.allreduce(buckets)
        return ([r.handle for r in reduced],
                [bk.views[j] for j in range(len(dense))], idx)

    def _ensure_bucketer(self, sig, idx):
        """Build (or reuse) the GradBucketer for this dense-gradient
        signature.  Shared by the fused allreduce AND the whole-step
        compiler so both lay residuals out identically — a checkpoint
        written under one path restores under the other."""
        from ..kvstore import GradBucketer
        if self._bucketer is None or self._bucket_sig != (sig, idx):
            mb = None
            if "MXNET_BUCKET_SIZE_MB" not in _os.environ:
                # env pin beats any persisted autotune decision; only an
                # UNSET env consults the tuner's measured pick for this
                # gradient signature (lazy import: autotune is optional
                # machinery, the trainer must not drag it in at import)
                from ..autotune import decisions as _decisions
                if _decisions.ENABLED:
                    mb = _decisions.knob(
                        _decisions.model_signature(sig),
                        "bucket_size_mb", None)
            cap = int(float(getenv("MXNET_BUCKET_SIZE_MB", 32.0)
                            if mb is None else mb) * 1024 * 1024)
            self._bucketer = GradBucketer(sig, cap)
            self._bucket_sig = (sig, idx)
            # the flat residual layout is a function of the bucket
            # layout — a signature change restarts error feedback
            self._residuals = None
        return self._bucketer

    def _ensure_scaler(self):
        """Dynamic loss-scaling state (MXNET_AMP=fp16): scale and
        consecutive-finite-step count as device scalars — the whole-step
        program reads, updates, and returns them functionally, so no
        per-step host sync ever inspects them.  Growth/backoff policy:
        x2 after MXNET_LOSS_SCALE_WINDOW consecutive finite steps, x0.5
        (floor 1.0) on any nonfinite gradient, that step skipped."""
        if self._scaler is None:
            self._scaler = self._make_scaler(
                getenv("MXNET_LOSS_SCALE_INIT", 65536.0), 0,
                getenv("MXNET_LOSS_SCALE_WINDOW", 200))
        return self._scaler

    @staticmethod
    def _make_scaler(scale, good, window):
        """The one place the scaler dict is constructed — fresh starts
        (_ensure_scaler) and checkpoint restores (load_states) must
        produce the identical structure."""
        return {
            "scale": _memory.register(
                jnp.asarray(float(scale), dtype=jnp.float32),
                tag="optimizer_state"),
            "good": _memory.register(
                jnp.asarray(int(good), dtype=jnp.int32),
                tag="optimizer_state"),
            "window": int(window),
        }

    @property
    def loss_scale(self) -> float:
        """Current dynamic loss scale (1.0 when fp16 scaling is off).
        Reading it syncs the device scalar — diagnostics/tests only,
        never the hot path."""
        if self._scaler is None:
            return 1.0
        return float(_np.asarray(self._scaler["scale"]))

    def _init_residuals(self, bk):
        """Fresh zero residuals sized to the bucket layout — unless
        load_states stashed checkpointed ones, which must match the
        current bucket signature exactly (a silent zero-reset would
        discard the checkpoint's error feedback)."""
        if self._pending_residuals is not None:
            saved_sig, arrays = self._pending_residuals
            # the param signature alone is not enough: a different
            # MXNET_BUCKET_SIZE_MB regroups the same params into
            # different flat buckets, so the residual ARRAY layout must
            # match too (else the jitted quantize dies on shapes)
            if saved_sig != self._bucket_sig or \
                    tuple(int(a.shape[0]) for a in arrays) != bk.sizes:
                raise MXNetError(
                    "Trainer.load_states: checkpointed compression "
                    "residuals were saved for a different parameter/"
                    f"bucket signature ({len(arrays)} buckets over "
                    f"{len(saved_sig[0])} dense params; current layout "
                    f"has {len(bk.sizes)} buckets over "
                    f"{len(self._bucket_sig[0])} dense params with "
                    "different shapes/dtypes/order). Resuming would "
                    "silently reset 2-bit error feedback — load states "
                    "saved from the same model and bucket layout "
                    "(MXNET_BUCKET_SIZE_MB included).")
            self._pending_residuals = None
            return [_memory.register(jnp.asarray(a),
                                     tag="compression_residual")
                    for a in arrays]
        return [_memory.register(jnp.zeros(n, dtype=jnp.float32),
                                 tag="compression_residual")
                for n in bk.sizes]

    def _update(self, ignore_stale_grad=False):
        from ..optimizer import FusedUpdater
        live, _, rsp_idx, _ = self._live_split()
        # pop the staged buckets BEFORE the stale check: if it raises,
        # a later update() must not consume the previous step's grads
        reduced, self._reduced = self._reduced, None
        reduced_rsp, self._reduced_rsp = self._reduced_rsp, None
        live = self._mask_stale(live, ignore_stale_grad)
        if self._update_on_kvstore and self._kv is not None:
            for i, param in live:
                self._kv.pull(i, out=param.list_data())
            self._clear_fresh(live)
            return
        upd = self._updaters[0]
        # one updater per device copy (parity: reference trainer keeps
        # len(contexts) updaters so every replica is updated)
        ncopies = max((len(p.list_data()) for _, p in live), default=1)
        while len(self._updaters) < ncopies:
            self._updaters.append(opt.get_updater(self._optimizer))
        done = list(live)
        fused_ok = self._fused and isinstance(upd, FusedUpdater)
        # update_all always runs f32 optimizer math — clear any sticky
        # whole-step AMP policy (a direct Trainer.step after AMP
        # whole-step training must not key, and loudly "recompile",
        # the update_all program under a precision it never traced)
        if fused_ok:
            for u in self._updaters:
                if u.dtype_policy != "f32":
                    u.dtype_policy = "f32"
        # row-sparse grad_stype params: one fused gather→step→scatter
        # dispatch over all sparse keys (ISSUE 20) when the updater is
        # fused and copies are single; MXNET_FUSED_TRAINER=0, multi-copy,
        # or non-fused optimizers keep the reference-shaped lazy per-key
        # loop for A/B runs
        rsp = [ip for ip in live if ip[0] in rsp_idx]
        if rsp:
            from ..ndarray import sparse as _sp

            def _as_rsp(g):
                return g if isinstance(g, _sp.RowSparseNDArray) \
                    else _sp.cast_storage(g, "row_sparse")
            if fused_ok and all(len(p.list_data()) == 1 for _, p in rsp):
                # _allreduce_grads(for_step=True) stages the merged
                # grads; a direct update() call consumes the raw per-key
                # grad buffers instead — same values single-worker
                sgrads = [_as_rsp(p.list_grad()[0])
                          if reduced_rsp is None or i not in reduced_rsp
                          else reduced_rsp[i] for i, p in rsp]
                with _flight.phase_span("fused_sparse_update",
                                        cat="optimizer",
                                        step=self._step_id, mem=True):
                    upd.update_sparse([i for i, _ in rsp], sgrads,
                                      [p.list_data()[0] for _, p in rsp])
            else:
                for i, param in rsp:
                    for u, arr, grad in zip(self._updaters,
                                            param.list_data(),
                                            param.list_grad()):
                        u(i, _as_rsp(grad), arr)
            live = [ip for ip in live if ip[0] not in rsp_idx]
            if not live:
                self._clear_fresh(done)
                return
        if fused_ok and all(len(p.list_data()) == 1 for _, p in live):
            if reduced is not None:
                flats, views, idx = reduced
                pos = {i: j for j, i in enumerate(idx)}
                # _allreduce_grads staged every dense live param in the
                # buckets; a param outside `idx` would train on its raw
                # UN-REDUCED grad buffer (the for_step path deliberately
                # never rewrites per-key grads), so fail loudly — a real
                # raise, not an assert, so python -O cannot strip it
                missing = [i for i, _ in live if i not in pos]
                if missing:
                    raise MXNetError(
                        f"staged gradient buckets cover params {idx} but "
                        f"the update set includes {missing} — the "
                        "allreduce and update steps saw different live "
                        "parameter sets")
                if live:
                    with _flight.phase_span("fused_update",
                                            cat="optimizer",
                                            step=self._step_id,
                                            mem=True):
                        upd.update_all(
                            [i for i, _ in live], flats,
                            [p.list_data()[0] for _, p in live],
                            grad_views=[views[pos[i]] for i, _ in live])
            else:
                with _flight.phase_span("fused_update", cat="optimizer",
                                        step=self._step_id, mem=True):
                    upd.update_all([i for i, _ in live],
                                   [p.list_grad()[0] for _, p in live],
                                   [p.list_data()[0] for _, p in live])
            self._clear_fresh(done)
            return
        if fused_ok and ncopies > 1 and \
                all(len(p.list_data()) == ncopies for _, p in live):
            # uniform multi-device copies: one fused program per copy
            # slot — O(#copies) dispatches, still O(1) in param count
            for c in range(ncopies):
                self._updaters[c].update_all(
                    [i for i, _ in live],
                    [p.list_grad()[c] for _, p in live],
                    [p.list_data()[c] for _, p in live])
            self._clear_fresh(done)
            return
        # legacy per-parameter loop (MXNET_FUSED_TRAINER=0, ragged device
        # copies, or optimizers without a fused_step)
        for i, param in live:
            for u, arr, grad in zip(self._updaters, param.list_data(),
                                    param.list_grad()):
                u(i, grad, arr)
        self._clear_fresh(done)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def get_states_bytes(self) -> bytes:
        """The complete durable optimizer state as one bytes payload:
        updater state (+ optimizer) and, when gradient compression is
        active, the error-feedback residuals — exactly what
        ``save_states`` writes to disk.  This is the trainer's
        checkpoint surface (`mxnet_tpu.checkpoint.save_trainer`)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            if self._kv._updater is None:
                raise MXNetError("no optimizer set")
            states = self._kv._updater.get_states(dump_optimizer=True)
        else:
            states = self._updaters[0].get_states(dump_optimizer=True)
        return self._wrap_states(states)

    def save_states(self, fname):
        from ..base import atomic_write
        atomic_write(fname, self.get_states_bytes())

    def _wrap_states(self, states: bytes) -> bytes:
        """Without compression or loss scaling the file is the raw
        updater-state pickle (format unchanged).  With compression
        active, the 2-bit error-feedback residuals ride along in a
        sentinel-keyed wrapper so a resumed run continues the same
        quantization trajectory instead of silently restarting from
        zero error; with fp16 dynamic loss scaling active (whole-step
        AMP), the scaler's scale/good-step state rides the same wrapper
        so a resumed run continues the same scale trajectory."""
        bucket = None
        if self._residuals is not None:
            bucket = {"sig": self._bucket_sig,
                      "residuals": [_np.asarray(r) for r in self._residuals]}
        elif self._pending_residuals is not None:
            saved_sig, arrays = self._pending_residuals
            bucket = {"sig": saved_sig,
                      "residuals": [_np.asarray(a) for a in arrays]}
        kv_res = {}
        if self._kv is not None and getattr(self._kv, "_residuals", None):
            # per-key residuals (legacy per-key path and the
            # update_on_kvstore fused pushpull both key them in the kv)
            kv_res = {k: _np.asarray(v)
                      for k, v in self._kv._residuals.items()}
        scaler = None
        if self._scaler is not None:
            scaler = {"scale": float(_np.asarray(self._scaler["scale"])),
                      "good": int(_np.asarray(self._scaler["good"])),
                      "window": int(self._scaler["window"])}
            if self._applied_ts is not None:
                scaler["ts_idx"] = list(self._applied_ts[0])
                scaler["ts"] = [int(t) for t in
                                _np.asarray(self._applied_ts[1])]
        if bucket is None and not kv_res and scaler is None:
            return states
        return pickle.dumps({"__mxt_trainer_states__": 1,
                             "updater": states,
                             "bucket": bucket,
                             "kv_residuals": kv_res,
                             "scaler": scaler})

    @staticmethod
    def _unwrap_states(payload: bytes):
        """(updater-state bytes, residual extras or None).  Raw legacy
        files unpickle to the updater's own dict/tuple — never a dict
        with the sentinel key — so detection cannot misfire."""
        try:
            obj = pickle.loads(payload)
        except Exception:
            return payload, None
        if isinstance(obj, dict) and obj.get("__mxt_trainer_states__") == 1:
            return obj["updater"], obj
        return payload, None

    def load_states(self, fname):
        with open(fname, "rb") as f:
            payload = f.read()
        self.set_states_bytes(payload)

    def set_states_bytes(self, payload: bytes):
        """Inverse of ``get_states_bytes`` (both raw legacy pickles and
        the residual-carrying sentinel wrapper)."""
        if not self._kv_initialized:
            self._init_kvstore()
        states, extra = self._unwrap_states(payload)
        # loading REPLACES the trainer's auxiliary training state: a
        # checkpoint written without fp16 must not inherit this
        # process's previous scaler/applied-ts trajectory (the next
        # save would otherwise persist the stale scale into the new
        # run's checkpoints)
        self._scaler = None
        self._applied_ts = None
        self._applied_ts_pending = None
        if self._update_on_kvstore:
            if self._kv._updater is None:
                raise MXNetError("no optimizer set")
            self._kv._updater.set_states(states)
            self._optimizer = self._kv._updater.optimizer
        else:
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        if extra is None:
            return
        scaler = extra.get("scaler")
        if scaler is not None:
            self._scaler = self._make_scaler(
                scaler["scale"], scaler["good"], scaler["window"])
            if scaler.get("ts") is not None:
                self._applied_ts_pending = (
                    tuple(scaler["ts_idx"]),
                    [int(t) for t in scaler["ts"]])
        kv_res = extra.get("kv_residuals") or {}
        if kv_res and self._kv is not None:
            self._kv._residuals = {k: jnp.asarray(v)
                                   for k, v in kv_res.items()}
        bucket = extra.get("bucket")
        if bucket is None:
            return
        self._pending_residuals = (bucket["sig"], bucket["residuals"])
        self._residuals = None
        if self._bucket_sig is not None:
            # a bucketer already exists: adopt (or reject) immediately
            # instead of deferring the mismatch to the next step
            self._residuals = self._init_residuals(self._bucketer)
