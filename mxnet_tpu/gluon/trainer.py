"""gluon.Trainer (parity: python/mxnet/gluon/trainer.py:27,108-127,156).

Applies an Optimizer to a ParameterDict; kvstore-backed when requested so
`KVStore('tpu_sync')` data parallelism works unmodified from gluon code.
"""
from __future__ import annotations

from ..base import MXNetError
from ..observability import metrics as _metrics
from ..observability.tracing import trace_span
from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        arg_arrays = {param.name: param.data() for param in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore, 1, arg_arrays)
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv = kvstore
        self._update_on_kvstore = update_on_kvstore and kvstore is not None
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler else self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with grads scaled by 1/batch_size.

        TPU hot path: all parameters update in O(1) XLA dispatches via
        KVStore.pushpull / FusedUpdater.update_all (replaces the reference's
        per-parameter kvstore push loop, gluon/trainer.py:191-226)."""
        with trace_span("trainer_step", cat="optimizer"):
            self._step(batch_size, ignore_stale_grad)

    def _step(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if self._kv is not None and self._update_on_kvstore:
            # row-sparse grad_stype params go through the kvstore per-key
            # sparse path (class-preserving push → lazy rsp optimizer on
            # the store) so untouched rows never decay
            rsp = [(i, p) for i, p in live
                   if getattr(p, "_grad_stype", "default") == "row_sparse"]
            if rsp:
                from ..ndarray import sparse as _sp
                for i, p in rsp:
                    # grads are already RowSparseNDArrays (rows-only
                    # autograd deposit); cast is only a legacy fallback
                    self._kv.pushpull(
                        i, [g if isinstance(g, _sp.RowSparseNDArray)
                            else _sp.cast_storage(g, "row_sparse")
                            for g in p.list_grad()],
                        out=p.list_data())
            dense = [ip for ip in live if ip not in rsp]
            if dense:
                self._kv.pushpull([i for i, _ in dense],
                                  [p.list_grad() for _, p in dense],
                                  out=[p.list_data() for _, p in dense])
            return
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kv is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kv.push(i, param.list_grad())
                if not self._update_on_kvstore:
                    self._kv.pull(i, param.list_grad())

    def _update(self, ignore_stale_grad=False):
        from ..optimizer import FusedUpdater
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if self._update_on_kvstore and self._kv is not None:
            for i, param in live:
                self._kv.pull(i, out=param.list_data())
            return
        upd = self._updaters[0]
        # one updater per device copy (parity: reference trainer keeps
        # len(contexts) updaters so every replica is updated)
        ncopies = max((len(p.list_data()) for _, p in live), default=1)
        while len(self._updaters) < ncopies:
            self._updaters.append(opt.get_updater(self._optimizer))
        # row-sparse grad_stype params take the lazy per-key sparse path
        # (dense autograd grad → RowSparse cast → row-wise update); the
        # rest go through the fused multi-tensor dispatch
        rsp = [(i, p) for i, p in live
               if getattr(p, "_grad_stype", "default") == "row_sparse"]
        if rsp:
            from ..ndarray import sparse as _sp
            for i, param in rsp:
                for u, arr, grad in zip(self._updaters, param.list_data(),
                                        param.list_grad()):
                    u(i, grad if isinstance(grad, _sp.RowSparseNDArray)
                      else _sp.cast_storage(grad, "row_sparse"), arr)
            live = [ip for ip in live if ip not in rsp]
            if not live:
                return
        if isinstance(upd, FusedUpdater) and \
                all(len(p.list_data()) == 1 for _, p in live):
            upd.update_all([i for i, _ in live],
                           [p.list_grad()[0] for _, p in live],
                           [p.list_data()[0] for _, p in live])
            return
        for i, param in live:
            for u, arr, grad in zip(self._updaters, param.list_data(),
                                    param.list_grad()):
                u(i, grad, arr)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.load_optimizer_states(fname)
            self._optimizer = self._kv._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
