"""gluon.model_zoo.vision (parity: gluon/model_zoo/vision/__init__.py:75-85).

alexnet, densenet, inception-v3, resnet v1/v2, squeezenet, vgg, mobilenet.
"""
from .resnet import (get_resnet, resnet18_v1, resnet34_v1, resnet50_v1,
                     resnet101_v1, resnet152_v1, resnet18_v2, resnet34_v2,
                     resnet50_v2, resnet101_v2, resnet152_v2,
                     ResNetV1, ResNetV2, BasicBlockV1, BasicBlockV2,
                     BottleneckV1, BottleneckV2)
from .alexnet import alexnet, AlexNet
from .vgg import (vgg11, vgg13, vgg16, vgg19, vgg11_bn, vgg13_bn, vgg16_bn,
                  vgg19_bn, get_vgg, VGG)
from .squeezenet import squeezenet1_0, squeezenet1_1, SqueezeNet
from .densenet import (densenet121, densenet161, densenet169, densenet201,
                       DenseNet)
from .inception import inception_v3, Inception3
from .mobilenet import (mobilenet1_0, mobilenet0_75, mobilenet0_5,
                        mobilenet0_25, get_mobilenet, MobileNet)


def get_model(name, **kwargs):
    """Create a model by name (parity: model_zoo.vision.get_model)."""
    models = {
        "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
        "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
        "resnet152_v1": resnet152_v1,
        "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
        "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
        "resnet152_v2": resnet152_v2,
        "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
        "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
        "vgg19_bn": vgg19_bn,
        "alexnet": alexnet,
        "densenet121": densenet121, "densenet161": densenet161,
        "densenet169": densenet169, "densenet201": densenet201,
        "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
        "inceptionv3": inception_v3,
        "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
        "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    }
    name = name.lower()
    if name not in models:
        raise ValueError(
            f"Model {name} is not supported. Available options are\n\t" +
            "\n\t".join(sorted(models.keys())))
    return models[name](**kwargs)
