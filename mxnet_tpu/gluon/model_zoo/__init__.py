"""gluon.model_zoo (parity: python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision
from . import transformer
from .vision import get_model
from .transformer import TransformerLM, transformer_lm
