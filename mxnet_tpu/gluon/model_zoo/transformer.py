"""Transformer language-model family (gluon).

Beyond-reference capability (SURVEY.md §2.3 long-context rows): the
reference (2017-era MXNet) predates transformers; this family is the
TPU-native flagship for the long-context story.  Design:

  - the whole decoder stack is one HybridBlock → a single jitted
    CachedOp forward + fused vjp (no per-layer dispatch),
  - attention can run as `dense` (materialized scores — XLA fuses the
    softmax chain) or `flash` (the Pallas `_contrib_flash_attention`
    kernel: O(T) memory online-softmax tiling on the MXU),
  - for sequence lengths beyond one chip, `mxnet_tpu.parallel`'s
    ring_attention / ulysses_attention shard the same math over the
    'sp' mesh axis (see parallel/sequence_parallel.py).

Pre-LN GPT-style decoder: x + MHSA(LN(x)); x + FFN(LN(x)).
"""
from __future__ import annotations



from .. import nn
from ..block import HybridBlock


def _write_frontier(F, tokens, pos, nxt, depth):
    """Scatter nxt (N, 1) into tokens (N, Tmax) at column pos+1 — the
    ONE frontier-write implementation (static greedy/sampled decode and
    the beam step all share it)."""
    oh = F.one_hot(pos + 1.0, depth=depth)
    return tokens * (1.0 - oh) + nxt * oh


def _kv_forward(F, net, tok, pos, caches):
    """The one-token decode stack walk shared by the KV and beam cells:
    (tok (N,1) ids, pos (1,), 2L caches (N,H,Tmax,dh)) -> (logits
    (N, V), updated caches).  Re-composes the SAME sub-blocks and
    parameters as the training forward."""
    x = net.tok(tok) + F.expand_dims(net.pos(pos), axis=0)
    new_caches = []
    for i, blk in enumerate(net.blocks._children):
        h = blk.ln1(x)
        qkv = blk.attn.qkv(h)                       # (N, 1, 3D)
        att, kc, vc = F.mha_decode_step(
            qkv, caches[2 * i], caches[2 * i + 1], pos,
            num_heads=blk.attn._h,
            impl=(blk.attn._type
                  if blk.attn._type in ("ring", "ulysses") else "dense"))
        new_caches += [kc, vc]
        x = x + blk.attn.proj(att)
        x = x + blk.ffn2(blk.ffn1(blk.ln2(x)))
    logits = net.head(net.ln_f(x))                  # (N, 1, V)
    return F.reshape(logits, (0, -1)), new_caches


class MultiHeadSelfAttention(HybridBlock):
    """Causal multi-head self-attention over (B, T, D) activations.

    attn_type: 'dense' | 'flash' (Pallas kernel, TPU hot path) |
    'ring' / 'ulysses' (sequence parallelism over the ambient
    `parallel.sp_scope(mesh)` — trace/call the model inside the scope).
    The sp types compose with eager blocks out of the box (the op
    reshards to the mesh and back); under a jitted executor the whole
    step must run over the same mesh (sharded inputs/params), which is
    how a real sp training step executes anyway.
    """

    def __init__(self, dim, num_heads, attn_type="dense", dropout=0.0,
                 **kw):
        super().__init__(**kw)
        assert dim % num_heads == 0
        if attn_type not in ("dense", "flash", "ring", "ulysses"):
            raise ValueError(f"unknown attn_type {attn_type!r}")
        self._h = num_heads
        self._dh = dim // num_heads
        self._type = attn_type
        with self.name_scope():
            self.qkv = nn.Dense(3 * dim, use_bias=True, flatten=False,
                                prefix="qkv_")
            self.proj = nn.Dense(dim, use_bias=True, flatten=False,
                                 prefix="proj_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        # the shape-dependent head split / mask / merge lives inside the
        # fused `_contrib_multihead_attention` op (ops always see
        # concrete shapes) — so this block hybridizes to a symbol graph
        qkv = self.qkv(x)                                   # (B,T,3D)
        # 'ring'/'ulysses' shard the sequence over the ambient
        # parallel.sp_scope mesh — trace the model inside the scope
        out = F.multihead_attention(qkv, num_heads=self._h, causal=True,
                                    impl=self._type)
        out = self.proj(out)
        return self.drop(out) if self.drop is not None else out


class TransformerBlock(HybridBlock):
    def __init__(self, dim, num_heads, ffn_dim, attn_type="dense",
                 dropout=0.0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.attn = MultiHeadSelfAttention(dim, num_heads, attn_type,
                                               dropout, prefix="attn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn1 = nn.Dense(ffn_dim, activation="relu", flatten=False,
                                 prefix="ffn1_")
            self.ffn2 = nn.Dense(dim, flatten=False, prefix="ffn2_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        h = self.ffn2(self.ffn1(self.ln2(x)))
        if self.drop is not None:
            h = self.drop(h)
        return x + h


class TransformerLM(HybridBlock):
    """GPT-style causal LM: token ids (B, T) → logits (B, T, vocab)."""

    def __init__(self, vocab, dim=128, num_layers=2, num_heads=4,
                 ffn_dim=None, max_len=512, attn_type="dense",
                 dropout=0.0, **kw):
        super().__init__(**kw)
        self._max_len = max_len
        with self.name_scope():
            self.tok = nn.Embedding(vocab, dim, prefix="tok_")
            self.pos = nn.Embedding(max_len, dim, prefix="pos_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            for i in range(num_layers):
                self.blocks.add(TransformerBlock(
                    dim, num_heads, ffn_dim or 4 * dim, attn_type,
                    dropout, prefix=f"l{i}_"))
            self.ln_f = nn.LayerNorm(prefix="lnf_")
            self.head = nn.Dense(vocab, flatten=False, prefix="head_")

    def hybrid_forward(self, F, tokens):
        if hasattr(tokens, "shape") and tokens.shape[1] > self._max_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len "
                f"{self._max_len} — positions would silently clamp")
        pos_ids = F.broadcast_like(
            F.expand_dims(F.arange_like(tokens, axis=1), 0), tokens)
        x = self.tok(tokens) + self.pos(pos_ids)
        x = self.blocks(x)
        return self.head(self.ln_f(x))


    def generate(self, prompt, max_new, temperature=0.0, rng=None,
                 static_shapes=None, kv_cache=False, top_k=0,
                 top_p=0.0):
        """Autoregressive decoding from `prompt` (B, T0) token ids.

        Greedy when temperature==0, else softmax sampling.

        static_shapes=True (default — the TPU path): tokens live in a
        fixed (B, max_len) buffer and every decode step is ONE cached
        hybridized program whose shapes never change, so XLA compiles
        once for the whole generation (greedy stays entirely on
        device).  Causality makes this exact: positions beyond the
        frontier hold zeros and cannot influence earlier logits
        (pinned by tests/test_transformer.py::test_causal_masking).

        static_shapes=False re-runs the forward on the growing prefix
        — one fresh XLA program PER LENGTH (catastrophic through a
        tunneled chip; kept as the debugging/parity reference).

        kv_cache=True decodes through per-layer K/V caches
        (`mha_decode_step`): O(Tmax*D) work per token instead of the
        full re-forward's O(Tmax^2*D) — the long-context decode path.
        One cached program per step; position and caches ride as data.
        """
        import numpy as np
        from ... import ndarray as F
        B, t0 = prompt.shape
        if t0 + max_new > self._max_len:
            raise ValueError(
                f"prompt length {t0} + max_new {max_new} "
                f"exceeds max_len {self._max_len}")
        if kv_cache:
            if static_shapes is not None:
                raise ValueError(
                    "kv_cache=True selects its own decode strategy; "
                    "combining it with an explicit static_shapes "
                    "would be silently ignored — pass one or the other")
            self._check_kv_supported()
            return self._generate_kv(prompt, max_new, temperature, rng,
                                     top_k, top_p)
        static_shapes = True if static_shapes is None else static_shapes
        if not static_shapes:
            toks = prompt
            for _ in range(max_new):
                logits = self(toks)                  # (B, T, V)
                last = logits[:, -1, :]
                nxt = self._sample(last, temperature, rng, top_k, top_p)
                toks = F.concat(toks, F.array(nxt, ctx=toks.context),
                                dim=1)
            return toks

        steps = self._decode_steps()
        pad = self._max_len - t0
        buf = prompt if pad == 0 else F.concat(
            prompt, F.zeros((B, pad), ctx=prompt.context), dim=1)
        for t in range(t0, t0 + max_new):
            pos = F.array([t - 1.0], ctx=prompt.context)
            if temperature == 0:
                buf = steps["greedy"](buf, pos)      # fully on device
            else:
                last = steps["logits"](buf, pos)     # (B, V)
                nxt = self._sample(last, temperature, rng, top_k, top_p)
                buf = steps["write"](buf, pos,
                                     F.array(nxt, ctx=prompt.context))
        return F.slice_axis(buf, axis=1, begin=0, end=t0 + max_new)

    def _init_caches(self, batch, ctx=None, dtype=None, sharded=None):
        """Zero per-layer K/V caches, (batch, H, max_len, dh) x 2L —
        the ONE cache-construction site (KV decode, beam search, and
        the decode-step export all share it).  sharded=(mesh, axis,
        kind) allocates each cache host->shards directly — 'ring'
        splits the sequence axis, 'ulysses' the head axis — so a
        cache larger than one device's memory is never materialized
        on one device."""
        from ... import ndarray as F
        blocks = self.blocks._children
        h, dh = blocks[0].attn._h, blocks[0].attn._dh
        shape = (batch, h, self._max_len, dh)
        if sharded is not None:
            import jax
            import numpy as np
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ...ndarray import NDArray
            mesh, axis, kind = sharded
            sh = NamedSharding(mesh, P(None, None, axis, None)
                               if kind == "ring"
                               else P(None, axis, None, None))
            host = np.zeros(shape, np.dtype(dtype or "float32"))
            return [NDArray(jax.device_put(host, sh))
                    for _ in range(2 * len(blocks))]
        kw = {}
        if ctx is not None:
            kw["ctx"] = ctx
        if dtype is not None:
            kw["dtype"] = dtype
        return [F.zeros(shape, **kw) for _ in range(2 * len(blocks))]

    def _check_kv_supported(self, allow_sp=True):
        """kv_cache decode support by attention type.  'ring' decodes
        over SEQUENCE-SHARDED caches (ring_decode_step; max_len must
        divide by the axis size) and 'ulysses' over HEAD-SHARDED
        caches (ulysses_decode_step; num_heads must divide) — both
        require an active parallel.sp_scope.  Beam search and the
        decode-step export are dense-cache paths (allow_sp=False)."""
        from ...parallel.sequence_parallel import current_sp_scope
        for blk in self.blocks._children:
            t = blk.attn._type
            if t not in ("ring", "ulysses"):
                continue
            if not allow_sp:
                raise NotImplementedError(
                    f"attn_type {t!r} is not supported on this decode "
                    "path — decode with static_shapes instead")
            mesh, axis = current_sp_scope()       # loud error if absent
            n = mesh.shape[axis]
            if t == "ring" and self._max_len % n:
                raise ValueError(
                    f"ring kv decode shards the cache over '{axis}' "
                    f"(size {n}); max_len {self._max_len} must be "
                    "divisible by it")
            if t == "ulysses" and blk.attn._h % n:
                raise ValueError(
                    f"ulysses kv decode shards heads over '{axis}' "
                    f"(size {n}); num_heads {blk.attn._h} must be "
                    "divisible by it")

    @staticmethod
    def _sample(last, temperature, rng, top_k=0, top_p=0.0):
        """Host-side next-token choice from (B, V) logits -> (B, 1).

        top_k > 0 keeps only the k most likely tokens; 0 < top_p <= 1
        keeps the smallest set whose cumulative probability reaches
        top_p (nucleus sampling, always at least the argmax); both
        filters compose (top-k first, then top-p)."""
        import numpy as np
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {top_p}")
        if temperature <= 0:
            return last.asnumpy().argmax(-1).astype(np.float32)[:, None]
        logits = last.asnumpy().astype(np.float64) / temperature
        out = np.empty((logits.shape[0], 1), np.float32)
        r = rng or np.random
        for b, row in enumerate(logits):
            if top_k and top_k < row.size:
                # exactly k survivors even under ties, chosen in
                # stable (first-occurrence) order so top_k=1 keeps
                # precisely the greedy argmax token
                keep = np.argsort(-row, kind="stable")[:top_k]
                masked = np.full_like(row, -np.inf)
                masked[keep] = row[keep]
                row = masked
            p = np.exp(row - row.max())
            p /= p.sum()
            if 0.0 < top_p < 1.0:
                order = np.argsort(-p)
                cum = np.cumsum(p[order])
                # keep the minimal prefix reaching top_p (>= 1 token)
                cut = int(np.searchsorted(cum, top_p)) + 1
                mask = np.zeros_like(p, bool)
                mask[order[:cut]] = True
                p = np.where(mask, p, 0.0)
                p /= p.sum()
            out[b, 0] = r.choice(p.size, p=p)
        return out

    def _decode_steps(self):
        """Build (once) the three hybridized decode-step blocks.

        Stored in __dict__ via a plain dict so Block.__setattr__ does
        not register them as children (the wrapper holds `self` as its
        sub-block — registration would create a parent<->child cycle).
        Only each wrapper's OWN hybrid flag is set: Block.hybridize()
        would recurse into the wrapped model and silently flip a
        deliberately-eager net into hybrid mode (symbol tracing routes
        through hybrid_forward regardless of the net's flag, so the
        wrapper's CachedOp doesn't need it).
        """
        cached = self.__dict__.get("_decode_step_cache")
        if cached is not None:
            return cached
        from ..block import HybridBlock

        outer = self

        def _write_at(F, tokens, pos, nxt):
            return _write_frontier(F, tokens, pos, nxt, outer._max_len)

        class _LogitsStep(HybridBlock):
            """(tokens (B,Tmax), pos (1,)) -> logits at pos, (B, V)."""

            def __init__(self, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.net = outer

            def hybrid_forward(self, F, tokens, pos):
                logits = self.net(tokens)            # (B, Tmax, V)
                last = F.take(logits, pos, axis=1)   # (B, 1, V)
                return F.reshape(last, (0, -1))

        class _GreedyStep(_LogitsStep):
            """One whole greedy step on device: read logits at pos,
            argmax, write the winner at pos+1; returns the updated
            (B, Tmax) buffer."""

            def hybrid_forward(self, F, tokens, pos):
                last = super().hybrid_forward(F, tokens, pos)
                nxt = F.argmax(last, axis=-1, keepdims=True)  # (B, 1)
                return _write_at(F, tokens, pos, nxt)

        class _WriteStep(HybridBlock):
            """(tokens, pos, nxt (B,1)) -> tokens with nxt at pos+1."""

            def hybrid_forward(self, F, tokens, pos, nxt):
                return _write_at(F, tokens, pos, nxt)

        steps = {"logits": _LogitsStep(), "greedy": _GreedyStep(),
                 "write": _WriteStep()}
        for blk in steps.values():
            blk._active = True                 # this wrapper only
        self.__dict__["_decode_step_cache"] = steps
        return steps

    def _kv_step(self):
        """Build (once) the KV-cache decode cell: ONE hybridized
        program computing (token_t, pos, *caches) -> (logits_t,
        *updated caches).  Re-composes the stack from the SAME
        sub-blocks/parameters as the training forward — LN, fused QKV,
        `mha_decode_step` (cache write + masked attention over the
        cache), projection, FFN, head — so decode weights can never
        drift from training weights.  Same child-registration and
        hybrid-flag rules as _decode_steps."""
        cached = self.__dict__.get("_kv_step_cache")
        if cached is not None:
            return cached
        from ..block import HybridBlock

        outer = self

        class _KVStep(HybridBlock):
            """(token_t (B,1), pos (1,), *caches) -> [head, *caches].
            greedy=True emits the argmax NEXT TOKEN as the head output
            (the whole step stays on device and its output feeds the
            next step without a host sync); greedy=False emits the
            (B, V) logits for host-side sampling."""

            def __init__(self, greedy, **kw):
                super().__init__(**kw)
                self._greedy = greedy
                with self.name_scope():
                    self.net = outer

            def hybrid_forward(self, F, tok, pos, *caches):
                logits, new_caches = _kv_forward(F, self.net, tok, pos,
                                                 caches)
                head = (F.argmax(logits, axis=-1, keepdims=True)
                        if self._greedy else logits)
                return [head] + new_caches

        steps = {"sample": _KVStep(False), "greedy": _KVStep(True)}
        for blk in steps.values():
            blk._active = True                  # this wrapper only
        self.__dict__["_kv_step_cache"] = steps
        return steps

    def _generate_kv(self, prompt, max_new, temperature, rng,
                     top_k=0, top_p=0.0):
        """KV-cache decode loop: prefill feeds prompt tokens through
        the same one-token cell that generates (cache fills as a side
        effect); every step reuses one compiled program.  Greedy keeps
        the whole loop on device — generated tokens come back as
        (B, 1) handles chained step-to-step and are fetched ONCE at
        the end (async dispatch: no per-token sync)."""
        import numpy as np
        from ... import ndarray as F
        B, t0 = prompt.shape
        ctx = prompt.context
        greedy = temperature == 0
        sp_type = next((blk.attn._type for blk in self.blocks._children
                        if blk.attn._type in ("ring", "ulysses")), None)
        if sp_type:
            # sequence-sharded caches: run the stack walk eagerly so
            # the ring decode op shards over the ambient sp mesh per
            # call (a jitted cell would need the whole step — params
            # included — placed on the mesh, the same rule as the sp
            # training forward)
            def run_step(cur, pos, caches):
                logits, nc = _kv_forward(F, self, cur, pos, caches)
                head = (F.argmax(logits, axis=-1, keepdims=True)
                        if greedy else logits)
                return head, nc
        else:
            cell = self._kv_step()["greedy" if greedy else "sample"]

            def run_step(cur, pos, caches):
                outs = cell(cur, pos, *caches)
                return outs[0], outs[1:]
        if sp_type:
            from ...parallel.sequence_parallel import current_sp_scope
            caches = self._init_caches(
                B, dtype=self.head.weight.dtype,
                sharded=current_sp_scope() + (sp_type,))
        else:
            caches = self._init_caches(B, ctx=ctx,
                                       dtype=self.head.weight.dtype)
        toks_np = prompt.asnumpy()
        pieces = [prompt]                  # (B, k) device-side chunks
        cur = F.array(toks_np[:, 0:1], ctx=ctx)
        for t in range(t0 + max_new - 1):
            pos = F.array([float(t)], ctx=ctx)
            head, caches = run_step(cur, pos, caches)
            if t + 1 < t0:                 # prefill: next prompt column
                cur = F.array(toks_np[:, t + 1:t + 2], ctx=ctx)
            elif greedy:
                cur = head                 # stays on device
                pieces.append(cur)
            else:
                nxt = self._sample(head, temperature, rng, top_k, top_p)
                cur = F.array(nxt, ctx=ctx)
                pieces.append(cur)
        return F.concat(*pieces, dim=1)

    def _beam_step(self, width):
        """Build (once per width) the beam-search step cell: ONE
        hybridized program that advances every beam one token —
        decode-stack logits, log-softmax, combined scores, top-k over
        (width*vocab), beam/cache reindex via gather, frontier write.
        Inputs: (cur (B*W,1), pos (1,), cum (B,W), buf (B*W,Tmax),
        offsets (B,W) = arange(B)*W, *caches); outputs: [cur', cum',
        buf', *caches'].  Same child-registration/hybrid-flag rules as
        the other decode wrappers."""
        cache = self.__dict__.setdefault("_beam_step_cache", {})
        if width in cache:
            return cache[width]
        from ..block import HybridBlock

        outer = self
        vocab = self.head._units

        class _BeamStep(HybridBlock):
            def __init__(self, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.net = outer

            def hybrid_forward(self, F, cur, pos, cum, buf, offsets,
                               *caches):
                W = width
                logits, new_caches = _kv_forward(F, self.net, cur, pos,
                                                 caches)        # (BW, V)
                V = vocab
                logp = F.log_softmax(logits, axis=-1)
                scores = F.reshape(cum, (-1, 1)) + logp         # (BW, V)
                scores = F.reshape(scores, (-1, W * V))         # (B, W*V)
                idx = F.topk(scores, k=W, ret_typ="indices", axis=-1,
                             is_ascend=False)                   # (B, W)
                # the value call re-sorts the same tensor inside the
                # same traced program — XLA CSE merges the two argsorts
                # into one, so this costs nothing at runtime
                new_cum = F.topk(scores, k=W, ret_typ="value", axis=-1,
                                 is_ascend=False)               # (B, W)
                beam_src = F.floor(idx / V)                     # (B, W)
                tok = idx - beam_src * V                        # (B, W)
                flat_src = F.reshape(beam_src + offsets, (-1,))  # (BW,)
                buf = F.take(buf, flat_src, axis=0)
                new_caches = [F.take(c, flat_src, axis=0)
                              for c in new_caches]
                tokcol = F.reshape(tok, (-1, 1))                # (BW, 1)
                buf = _write_frontier(F, buf, pos, tokcol,
                                      outer._max_len)
                return [tokcol, new_cum, buf] + new_caches

        step = _BeamStep()
        step._active = True                     # this wrapper only
        cache[width] = step
        return step

    def export_decode_step(self, prefix, batch_size=1):
        """Write the KV decode cell as a standalone predict artifact —
        `{prefix}-symbol.json` + `{prefix}-0000.params` — loadable by
        `mxnet_tpu.predictor.Predictor` AND the flat-C inference ABI
        (`libmxt_predict.so`, parity c_predict_api.h): a plain-C
        program can run LM decoding by looping SetInput(token, pos,
        caches) / Forward / GetOutput(logits, caches), feeding the
        cache outputs back in.

        Inputs (in order): data0 token (B, 1), data1 pos (1,),
        data2..data{2L+1} per-layer K/V caches (B, H, max_len, dh).
        Outputs: [logits (B, vocab), *updated caches].  Returns the
        input-name list.
        """
        from ... import ndarray as F
        from ...model import save_checkpoint
        self._check_kv_supported(allow_sp=False)
        step = self._kv_step()["sample"]
        tok = F.zeros((batch_size, 1))
        pos = F.array([0.0])
        caches = self._init_caches(batch_size)
        inputs, out = step._get_graph(tok, pos, *caches)
        aux_names = set(out.list_auxiliary_states())
        params = {name: p.data()
                  for name, p in step.collect_params().items()}
        save_checkpoint(
            prefix, 0, out,
            {k: v for k, v in params.items() if k not in aux_names},
            {k: v for k, v in params.items() if k in aux_names})
        return [i.name for i in inputs]

    def beam_search(self, prompt, max_new, beam=4):
        """Beam-search decoding over the KV-cache cell.

        Returns (sequences (B, T0+max_new), log_probs (B,)): the
        highest-scoring beam per example and its total log-probability
        over the generated positions.  Every step is one cached
        program: beams ride as batch rows (B*beam), the top-k over
        combined scores, the beam/cache reindex (gather) and the
        frontier write all stay on device; the host fetches once at
        the end.  No EOS handling — the toy LM family has no reserved
        ids; all beams run the full max_new (document-level parity:
        the 2017 reference has no decoder at all).
        """
        import numpy as np
        from ... import ndarray as F
        if beam < 1:
            raise ValueError("beam must be >= 1")
        B, t0 = prompt.shape
        if t0 + max_new > self._max_len:
            raise ValueError(
                f"prompt length {t0} + max_new {max_new} "
                f"exceeds max_len {self._max_len}")
        self._check_kv_supported(allow_sp=False)
        W = beam
        ctx = prompt.context
        prefill = self._kv_step()["sample"]
        step = self._beam_step(W)
        # prefill at B rows (beams are identical over the prompt), then
        # tile the caches to B*W — prompt-dominated decodes must not pay
        # the beam width during prefill
        caches = self._init_caches(B, ctx=ctx,
                                   dtype=self.head.weight.dtype)
        prompt_np = prompt.asnumpy()             # (B, t0)
        cur = F.array(prompt_np[:, 0:1], ctx=ctx)
        for t in range(t0 - 1):                  # prefill prompt tokens
            outs = prefill(cur, F.array([float(t)], ctx=ctx), *caches)
            caches = outs[1:]
            cur = F.array(prompt_np[:, t + 1:t + 2], ctx=ctx)
        caches = [F.repeat(c, repeats=W, axis=0) for c in caches]
        toks_np = np.repeat(prompt_np, W, axis=0)          # (BW, t0)
        pad = self._max_len - t0
        buf = F.array(np.concatenate(
            [toks_np, np.zeros((B * W, pad), "f")], axis=1)
            if pad else toks_np, ctx=ctx)
        # only beam 0 contributes until beams diverge
        cum = F.array(np.tile([0.0] + [-1e30] * (W - 1), (B, 1)), ctx=ctx)
        offsets = F.array(np.arange(B)[:, None] * W *
                          np.ones((1, W), "f"), ctx=ctx)
        cur = F.array(toks_np[:, t0 - 1:t0], ctx=ctx)
        for t in range(t0 - 1, t0 + max_new - 1):
            outs = step(cur, F.array([float(t)], ctx=ctx), cum, buf,
                        offsets, *caches)
            cur, cum, buf, caches = outs[0], outs[1], outs[2], outs[3:]
        buf_np = buf.asnumpy()[:, :t0 + max_new].reshape(B, W, -1)
        cum_np = cum.asnumpy()                   # (B, W), sorted desc
        best = buf_np[:, 0, :]                   # topk is descending
        return (F.array(best, ctx=ctx),
                F.array(cum_np[:, 0], ctx=ctx))


def transformer_lm(vocab, **kwargs):
    return TransformerLM(vocab, **kwargs)
