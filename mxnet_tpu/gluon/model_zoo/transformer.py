"""Transformer language-model family (gluon).

Beyond-reference capability (SURVEY.md §2.3 long-context rows): the
reference (2017-era MXNet) predates transformers; this family is the
TPU-native flagship for the long-context story.  Design:

  - the whole decoder stack is one HybridBlock → a single jitted
    CachedOp forward + fused vjp (no per-layer dispatch),
  - attention can run as `dense` (materialized scores — XLA fuses the
    softmax chain) or `flash` (the Pallas `_contrib_flash_attention`
    kernel: O(T) memory online-softmax tiling on the MXU),
  - for sequence lengths beyond one chip, `mxnet_tpu.parallel`'s
    ring_attention / ulysses_attention shard the same math over the
    'sp' mesh axis (see parallel/sequence_parallel.py).

Pre-LN GPT-style decoder: x + MHSA(LN(x)); x + FFN(LN(x)).
"""
from __future__ import annotations



from .. import nn
from ..block import HybridBlock


class MultiHeadSelfAttention(HybridBlock):
    """Causal multi-head self-attention over (B, T, D) activations.

    attn_type: 'dense' | 'flash' (Pallas kernel, TPU hot path) |
    'ring' / 'ulysses' (sequence parallelism over the ambient
    `parallel.sp_scope(mesh)` — trace/call the model inside the scope).
    The sp types compose with eager blocks out of the box (the op
    reshards to the mesh and back); under a jitted executor the whole
    step must run over the same mesh (sharded inputs/params), which is
    how a real sp training step executes anyway.
    """

    def __init__(self, dim, num_heads, attn_type="dense", dropout=0.0,
                 **kw):
        super().__init__(**kw)
        assert dim % num_heads == 0
        if attn_type not in ("dense", "flash", "ring", "ulysses"):
            raise ValueError(f"unknown attn_type {attn_type!r}")
        self._h = num_heads
        self._dh = dim // num_heads
        self._type = attn_type
        with self.name_scope():
            self.qkv = nn.Dense(3 * dim, use_bias=True, flatten=False,
                                prefix="qkv_")
            self.proj = nn.Dense(dim, use_bias=True, flatten=False,
                                 prefix="proj_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        # the shape-dependent head split / mask / merge lives inside the
        # fused `_contrib_multihead_attention` op (ops always see
        # concrete shapes) — so this block hybridizes to a symbol graph
        qkv = self.qkv(x)                                   # (B,T,3D)
        # 'ring'/'ulysses' shard the sequence over the ambient
        # parallel.sp_scope mesh — trace the model inside the scope
        out = F.multihead_attention(qkv, num_heads=self._h, causal=True,
                                    impl=self._type)
        out = self.proj(out)
        return self.drop(out) if self.drop is not None else out


class TransformerBlock(HybridBlock):
    def __init__(self, dim, num_heads, ffn_dim, attn_type="dense",
                 dropout=0.0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.attn = MultiHeadSelfAttention(dim, num_heads, attn_type,
                                               dropout, prefix="attn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn1 = nn.Dense(ffn_dim, activation="relu", flatten=False,
                                 prefix="ffn1_")
            self.ffn2 = nn.Dense(dim, flatten=False, prefix="ffn2_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        h = self.ffn2(self.ffn1(self.ln2(x)))
        if self.drop is not None:
            h = self.drop(h)
        return x + h


class TransformerLM(HybridBlock):
    """GPT-style causal LM: token ids (B, T) → logits (B, T, vocab)."""

    def __init__(self, vocab, dim=128, num_layers=2, num_heads=4,
                 ffn_dim=None, max_len=512, attn_type="dense",
                 dropout=0.0, **kw):
        super().__init__(**kw)
        self._max_len = max_len
        with self.name_scope():
            self.tok = nn.Embedding(vocab, dim, prefix="tok_")
            self.pos = nn.Embedding(max_len, dim, prefix="pos_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            for i in range(num_layers):
                self.blocks.add(TransformerBlock(
                    dim, num_heads, ffn_dim or 4 * dim, attn_type,
                    dropout, prefix=f"l{i}_"))
            self.ln_f = nn.LayerNorm(prefix="lnf_")
            self.head = nn.Dense(vocab, flatten=False, prefix="head_")

    def hybrid_forward(self, F, tokens):
        if hasattr(tokens, "shape") and tokens.shape[1] > self._max_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len "
                f"{self._max_len} — positions would silently clamp")
        pos_ids = F.broadcast_like(
            F.expand_dims(F.arange_like(tokens, axis=1), 0), tokens)
        x = self.tok(tokens) + self.pos(pos_ids)
        x = self.blocks(x)
        return self.head(self.ln_f(x))


    def generate(self, prompt, max_new, temperature=0.0, rng=None):
        """Autoregressive decoding from `prompt` (B, T0) token ids.

        Greedy when temperature==0, else softmax sampling.  Each step
        re-runs the (hybridized, cached) forward on the growing prefix —
        correct-by-construction causal decoding; a KV-cache fast path is
        a TPU-side optimization that does not change this API.
        """
        import numpy as np
        from ... import ndarray as F
        if prompt.shape[1] + max_new > self._max_len:
            raise ValueError(
                f"prompt length {prompt.shape[1]} + max_new {max_new} "
                f"exceeds max_len {self._max_len}")
        toks = prompt
        for _ in range(max_new):
            logits = self(toks)                      # (B, T, V)
            last = logits[:, -1, :]
            if temperature > 0:
                p = F.softmax(last / temperature, axis=-1).asnumpy()
                nxt = np.array([
                    (rng or np.random).choice(p.shape[-1], p=row / row.sum())
                    for row in p], dtype=np.float32)[:, None]
            else:
                nxt = last.asnumpy().argmax(-1).astype(np.float32)[:, None]
            toks = F.concat(toks, F.array(nxt, ctx=toks.context), dim=1)
        return toks


def transformer_lm(vocab, **kwargs):
    return TransformerLM(vocab, **kwargs)
