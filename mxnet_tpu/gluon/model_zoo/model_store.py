"""Model zoo file store (parity: python/mxnet/gluon/model_zoo/model_store.py).

The reference downloads `{name}-{sha1[:8]}.params` from the Apache S3
bucket.  This build runs on zero-egress hosts, so `get_model_file` resolves
ONLY against the local cache (default `~/.mxnet/models`, override with
`MXNET_HOME`): pre-placed or converted checkpoints with the reference
naming slot straight in, and a missing file raises an actionable error
instead of attempting a download.  The sha1 table is kept so cache file
names match the reference exactly.
"""
from __future__ import annotations

import os

from ...base import MXNetError

__all__ = ["get_model_file", "purge"]

# name -> sha1 (reference model_store.py table; kept for cache naming)
_model_sha1 = {name: checksum for checksum, name in [
    ('44335d1f0046b328243b32a26a4fbd62d9057b45', 'alexnet'),
    ('f27dbf2dbd5ce9a80b102d89c7483342cd33cb31', 'densenet121'),
    ('b6c8a95717e3e761bd88d145f4d0a214aaa515dc', 'densenet161'),
    ('2603f878403c6aa5a71a124c4a3307143d6820e9', 'densenet169'),
    ('1cdbc116bc3a1b65832b18cf53e1cb8e7da017eb', 'densenet201'),
    ('ed47ec45a937b656fcc94dabde85495bbef5ba1f', 'inceptionv3'),
    ('d2b128fa89477c2e20061607a53a8d9f66ce239d', 'resnet101_v1'),
    ('6562166cd597a6328a32a0ce47bb651df80b3bbb', 'resnet152_v1'),
    ('38d6d423c22828718ec3397924b8e116a03e6ac0', 'resnet18_v1'),
    ('4dc2c2390a7c7990e0ca1e53aeebb1d1a08592d1', 'resnet34_v1'),
    ('2a903ab21260c85673a78fe65037819a843a1f43', 'resnet50_v1'),
    ('8aacf80ff4014c1efa2362a963ac5ec82cf92d5b', 'resnet18_v2'),
    ('0ed3cd06da41932c03dea1de7bc2506ef3fb97b3', 'resnet34_v2'),
    ('eb7a368774aa34a12ed155126b641ae7556dad9d', 'resnet50_v2'),
    ('264ba4970a0cc87a4f15c96e25246a1307caf523', 'squeezenet1.0'),
    ('33ba0f93753c83d86e1eb397f38a667eaf2e9376', 'squeezenet1.1'),
    ('dd221b160977f36a53f464cb54648d227c707a05', 'vgg11'),
    ('ee79a8098a91fbe05b7a973fed2017a6117723a8', 'vgg11_bn'),
    ('6bc5de58a05a5e2e7f493e2d75a580d83efde38c', 'vgg13'),
    ('7d97a06c3c7a1aecc88b6e7385c2b373a249e95e', 'vgg13_bn'),
    ('649467530119c0f78c4859999e264e7bf14471a9', 'vgg16'),
    ('6b9dbe6194e5bfed30fd7a7c9a71f7e5a276cb14', 'vgg16_bn'),
    ('f713436691eee9a20d70a145ce0d53ed24bf7399', 'vgg19'),
    ('9730961c9cea43fd7eeefb00d792e386c45847d6', 'vgg19_bn'),
    ('b55eb6327e1c1d8db398b11e193dd1d0e6d78779', 'mobilenet0.25'),
    ('a3bdcbcbe1e40c1d2969aa2a0f0dd92a0a1b2a0c', 'mobilenet0.5'),
    ('cb10ca05ae25a4942bf103dd09eb8c80a2f0b2f6', 'mobilenet0.75'),
    ('e392fe05eec9ec5f0692a8b0c1bd4e9c3b155dd1', 'mobilenet1.0')]}


def short_hash(name: str) -> str:
    if name not in _model_sha1:
        raise MXNetError(f"Pretrained model for {name} is not available.")
    return _model_sha1[name][:8]


def default_root() -> str:
    return os.path.join(os.environ.get("MXNET_HOME",
                                       os.path.expanduser("~/.mxnet")),
                        "models")


def get_model_file(name: str, root: str = None) -> str:
    """Return the local path of the pretrained parameter file
    `{name}-{sha1[:8]}.params` (also accepts plain `{name}.params`).

    Zero-egress divergence from the reference: no download is attempted —
    place converted reference checkpoints under `root` (default
    `$MXNET_HOME/models` or `~/.mxnet/models`).
    """
    root = os.path.expanduser(root or default_root())
    candidates = [os.path.join(root, f"{name}-{short_hash(name)}.params"),
                  os.path.join(root, f"{name}.params")]
    for path in candidates:
        if os.path.exists(path):
            return path
    raise MXNetError(
        f"Pretrained weights for '{name}' not found locally (looked for "
        f"{candidates}). This host has no network egress: convert/copy the "
        f"reference checkpoint into place, e.g. "
        f"`cp {name}.params {candidates[0]}`.")


def purge(root: str = None) -> None:
    """Remove cached pretrained files (parity: model_store.purge)."""
    root = os.path.expanduser(root or default_root())
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
