"""`mx.gluon` namespace (parity: python/mxnet/gluon/__init__.py)."""
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock, CachedOp
from .trainer import Trainer
from . import wholestep
from .wholestep import WholeStepCompiler
from . import supervisor
from .supervisor import TrainingSupervisor
from . import nn
from . import rnn
from . import loss
from . import data
from . import utils
from . import model_zoo
