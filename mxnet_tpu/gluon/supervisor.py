"""TrainingSupervisor: fault-tolerant training steps (ISSUE 12).

PR 6 made *serving* survive chaos; the training loop — the thing a
production jax_graft system runs for days — still died on the first
transient device error, hung forever on a wedged chip, and (since PR 10
donates the whole step) could leave *poisoned buffers* behind a failed
dispatch: the params may already be consumed by XLA when the error
surfaces.  This module is the training-side twin of the serving
resilience tier (MXNet leans on the KVStore server as the recovery
consistency point for exactly this failure class, arxiv 1512.01274; the
TF paper treats checkpoint-mediated recovery from worker failure as a
first-class requirement, arxiv 1605.08695 §4.4):

  * **typed fault classification** — every step failure routes through
    ``resilience.classify``: *transient* (UNAVAILABLE tunnel, RPC
    deadline, injected chaos) retries; *oom*
    (``DeviceMemoryError``, already post-mortemed by the PR 9 ledger)
    and *permanent* (trace/user errors) propagate immediately.
  * **donation-safe retry** — a bounded rolling host snapshot of
    params + optimizer state + compression residuals + loss scaler
    (every ``MXNET_SUPERVISE_SNAPSHOT_STEPS``, via the checkpoint
    layer's eager device→host ``snapshot_state``) plus the window of
    batch references since the snapshot.  On a transient failure the
    supervisor restores the snapshot, replays the window, and
    re-executes the failed step — donated buffers a failed whole-step
    dispatch consumed are rebuilt from host copies, and an f32 retry
    run is bitwise-identical to an uninterrupted one (deterministic
    steps; stochastic models re-draw RNG and match statistically).
  * **divergence watchdog** — ``MXNET_SUPERVISE_DIVERGE_PATIENCE``
    consecutive nonfinite losses triggers ONE rate-limited post-mortem
    (flight ring + HBM ledger report, the PR 8/9 surfaces) and then
    either a typed ``DivergenceError`` or a rewind to the last
    snapshot, per ``MXNET_SUPERVISE_ON_DIVERGE=raise|rewind``.
  * **stall watchdog** — steps execute on a dedicated worker thread
    while the caller waits with a deadline derived from the
    step-duration EWMA (the supervisor's own, seeded/maxed with the
    flight recorder's ``trainer_step``/``whole_step`` watch EWMAs).  A
    step that blows ``MXNET_SUPERVISE_STALL_FACTOR`` × EWMA (floored at
    ``MXNET_SUPERVISE_STALL_MIN_S``) post-mortems and raises a typed
    ``TrainingStalledError`` instead of hanging forever; the supervisor
    is then poisoned (the wedged dispatch may still own the device).
  * **preemption** — ``install_preemption_hook`` upgrades the PR 5
    SIGTERM hook to fire *through* the supervisor: mid-step the
    emergency save uses the last consistent host snapshot instead of
    live (possibly half-updated, possibly donated) device buffers.

Overhead contract (the METRICS_ENABLED discipline):
``MXNET_SUPERVISE=0`` reduces ``step()`` to ONE module-global boolean
test and a direct call.  Enabled, a steady-state step costs one
worker-thread handoff, one EWMA update, and (every
``MXNET_SUPERVISE_CHECK_EVERY`` steps) one host read of the loss; the
bench ``chaos`` rider pins the total at ≤2% steps/s.

::

    sup = mx.gluon.TrainingSupervisor(stepper.step, trainer=trainer,
                                      params=net)
    uninstall = sup.install_preemption_hook(manager)
    for x, y in batches:
        loss = sup.step(x, y)     # retries transients, watches health
"""
from __future__ import annotations

import logging
import math
import queue as _queue
import threading
import time
from typing import Callable, Optional

import numpy as _np

from ..base import MXNetError, getenv
from ..checkpoint import layout as _layout
from ..observability import flight as _flight
from ..observability import goodput as _goodput
from ..observability import journal as _journal
from ..observability import metrics as _metrics
from .. import resilience as _res
from ..resilience import (DivergenceError, StepRetriesExhausted,
                          TrainingStalledError)

log = logging.getLogger(__name__)

__all__ = ["ENABLED", "enable", "disable", "enabled", "TrainingSupervisor"]

# -- the fast-path switch ----------------------------------------------------
# MXNET_SUPERVISE=0: every supervisor hook is one module-global boolean
# test; step() delegates straight to the wrapped step_fn.
ENABLED: bool = bool(getenv("MXNET_SUPERVISE", True))

_EWMA_ALPHA = 0.3   # same smoothing/warmup as the flight watchdog —
_EWMA_WARMUP = 5    # the two EWMAs must agree on what "normal" means

#: flight phases whose warmed EWMA seeds the stall deadline (whichever
#: step mode ran, its phase is warm)
_STEP_PHASES = ("trainer_step", "whole_step")


def enabled() -> bool:
    return ENABLED


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def _finite(value) -> bool:
    """Host-side finiteness of a step's returned loss.  NDArray / jax /
    numpy arrays read via ``np.asarray`` (on the CPU backend this is
    ~zero-copy; on TPU it transfers only the loss array) — no extra
    compiled dispatch.  Unrecognized types count as finite (the
    supervisor never fails a step it cannot interpret)."""
    if value is None:
        return True
    if isinstance(value, (float, int)):
        return math.isfinite(value)
    data = getattr(value, "_data", value)  # NDArray -> jax array
    try:
        return bool(_np.isfinite(_np.asarray(data)).all())
    except Exception:  # noqa: BLE001 — non-numeric step results
        return True


class TrainingSupervisor:
    """Supervise a training-step callable with typed-fault retry,
    divergence and stall watchdogs, and snapshot-consistent preemption.

    Parameters
    ----------
    step_fn : callable
        One training step: ``step_fn(*args, **kw) -> loss`` (the loss —
        NDArray / scalar — feeds the divergence watchdog; other return
        types are passed through unchecked).  Typical values:
        ``WholeStepCompiler(...).step``, or a closure doing
        record/backward/``Trainer.step``.
    trainer : gluon.Trainer, optional
        Snapshots ``get_states_bytes()`` (optimizer state, 2-bit
        residuals, fp16 scaler) and restores via ``set_states_bytes``.
    params : Block | ParameterDict | dict, optional
        The model parameters (aux states included) to snapshot/restore.
    snapshot_fn / restore_fn : callable, optional
        Override the state capture entirely: ``snapshot_fn() -> {name:
        value}`` (arrays/bytes, fed to ``layout.snapshot_state``) and
        ``restore_fn(state_dict)``.  Used by ``for_module``.
    steps_per_call : int, optional
        TRAINING steps one ``step_fn`` invocation advances — pass K
        when supervising ``SuperStepCompiler.superstep`` (the retry
        unit is then the whole superstep: snapshots land on superstep
        boundaries, the replay window holds K-batch groups, and a
        restore rewinds to the last superstep boundary).  The
        ``snapshot_steps`` budget keeps counting training steps: the
        snapshot cadence in CALLS is ``ceil(snapshot_steps /
        steps_per_call)``.  Default 1.
    snapshot_steps / retries / backoff_s / diverge_patience /
    on_diverge / check_every / stall_factor / stall_min_s : optional
        Override the corresponding ``MXNET_SUPERVISE_*`` env defaults
        (see docs/training_resilience.md for the tuning guide).
    """

    def __init__(self, step_fn: Callable, trainer=None, params=None,
                 snapshot_fn: Optional[Callable] = None,
                 restore_fn: Optional[Callable] = None,
                 snapshot_steps: Optional[int] = None,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 diverge_patience: Optional[int] = None,
                 on_diverge: Optional[str] = None,
                 check_every: Optional[int] = None,
                 stall_factor: Optional[float] = None,
                 stall_min_s: Optional[float] = None,
                 steps_per_call: Optional[int] = None):
        self._step_fn = step_fn
        self._trainer = trainer
        self._pd = None
        if params is not None:
            from ..checkpoint.manager import _as_param_dict
            self._pd = _as_param_dict(params)
        self._snapshot_fn = snapshot_fn
        self._restore_fn = restore_fn
        if (self._pd is None and trainer is None
                and (snapshot_fn is None) != (restore_fn is None)):
            raise MXNetError("snapshot_fn and restore_fn come as a pair")
        self.snapshot_steps = int(getenv("MXNET_SUPERVISE_SNAPSHOT_STEPS",
                                         50)) \
            if snapshot_steps is None else int(snapshot_steps)
        if self.snapshot_steps < 1:
            raise MXNetError("snapshot_steps must be >= 1")
        self.steps_per_call = 1 if steps_per_call is None \
            else int(steps_per_call)
        if self.steps_per_call < 1:
            raise MXNetError("steps_per_call must be >= 1")
        self.retries = int(getenv("MXNET_SUPERVISE_RETRIES", 2)) \
            if retries is None else int(retries)
        self.backoff_s = float(getenv("MXNET_SUPERVISE_RETRY_BACKOFF_S",
                                      0.05)) \
            if backoff_s is None else float(backoff_s)
        self.diverge_patience = int(getenv(
            "MXNET_SUPERVISE_DIVERGE_PATIENCE", 3)) \
            if diverge_patience is None else int(diverge_patience)
        od = str(getenv("MXNET_SUPERVISE_ON_DIVERGE", "raise")).lower() \
            if on_diverge is None else str(on_diverge).lower()
        if od not in ("raise", "rewind"):
            raise MXNetError(
                f"MXNET_SUPERVISE_ON_DIVERGE must be raise|rewind, got {od!r}")
        self.on_diverge = od
        self.check_every = int(getenv("MXNET_SUPERVISE_CHECK_EVERY", 1)) \
            if check_every is None else int(check_every)
        self.stall_factor = float(getenv("MXNET_SUPERVISE_STALL_FACTOR",
                                         60.0)) \
            if stall_factor is None else float(stall_factor)
        self.stall_min_s = float(getenv("MXNET_SUPERVISE_STALL_MIN_S",
                                        30.0)) \
            if stall_min_s is None else float(stall_min_s)

        # rolling snapshot: (step_count at capture, snapshot_state dict)
        self._snap: Optional[tuple] = None
        # batch windows since the snapshot: [(args, kwargs)], replayed
        # after a restore.  Bounded: cleared at every snapshot, so it
        # never holds more than snapshot_steps entries
        self._window: list = []
        self._step_count = 0
        self._nonfinite = 0
        self._retry_warned = False
        self._in_step = False
        self._stalled: Optional[str] = None  # poison reason after a stall
        # own step-duration EWMA (the flight recorder's may be disabled)
        self._ewma = 0.0
        self._ewma_n = 0
        # lazily-started step executor thread (the stall guard): jobs
        # and results are sequenced — at most one job in flight, and a
        # stall permanently poisons the supervisor, so a late result
        # from a wedged dispatch can never be matched to a new job
        self._work_q: Optional[_queue.SimpleQueue] = None
        self._result_q: Optional[_queue.SimpleQueue] = None
        self._worker: Optional[threading.Thread] = None

    # -- construction helpers ------------------------------------------------
    @classmethod
    def for_module(cls, module, **kw) -> "TrainingSupervisor":
        """Supervise a ``Module``'s fit step: ``step(batch)`` runs
        ``forward_backward`` + ``update`` with the same retry/stall
        machinery; snapshots pack ``get_params`` + optimizer-state
        bytes (what ``Module.fit(supervise=True)`` uses).

        The divergence watchdog defaults OFF here (``check_every=0``):
        the step has no loss to watch — module outputs are raw head
        activations, where ±inf can be legitimate (log-prob masks) and
        saturated-but-finite values can hide a diverged loss.  Pass
        ``check_every`` explicitly to watch the outputs anyway."""
        kw.setdefault("check_every", 0)
        from ..faultinject import fire as _fi_fire

        def step_fn(batch):
            # same chaos site as the gluon paths: one fire per step
            _fi_fire("trainer.step")
            module.forward_backward(batch)
            module.update()
            outs = module.get_outputs()
            return outs[0] if outs else None

        def snapshot_fn():
            from ..checkpoint.manager import pack_module_state
            arg_p, aux_p = module.get_params()
            opt_b = module.get_optimizer_states_bytes() \
                if hasattr(module, "get_optimizer_states_bytes") else None
            return pack_module_state(None, arg_p, aux_p,
                                     optimizer_states=opt_b)

        def restore_fn(state):
            from .. import ndarray as nd
            from ..checkpoint.manager import unpack_module_state
            arg_p, aux_p, opt_b, _ = unpack_module_state(state)
            module.set_params({k: nd.array(v) for k, v in arg_p.items()},
                              {k: nd.array(v) for k, v in aux_p.items()})
            if opt_b is not None and \
                    hasattr(module, "set_optimizer_states_bytes"):
                module.set_optimizer_states_bytes(opt_b)

        return cls(step_fn, snapshot_fn=snapshot_fn,
                   restore_fn=restore_fn, **kw)

    # -- public entry --------------------------------------------------------
    def step(self, *args, **kw):
        """Run one supervised training step.  With ``MXNET_SUPERVISE=0``
        this is exactly ``step_fn(*args, **kw)`` — one boolean test."""
        if not ENABLED:
            return self._step_fn(*args, **kw)
        if self._stalled is not None:
            raise TrainingStalledError(
                f"supervisor poisoned by an earlier stall ({self._stalled})"
                " — the wedged dispatch may still own the device; restart "
                "the process and resume from the last checkpoint",
                step=self._step_count)
        self._maybe_snapshot()
        if self._can_restore:
            # the replay window only exists to rebuild state after a
            # snapshot restore; without a snapshot surface it would
            # just grow one batch reference per step forever
            self._window.append((args, kw))
        try:
            out = self._attempt(args, kw)
        except BaseException:
            # the failed batch must not replay on a later retry of a
            # DIFFERENT step — the caller decides whether to resubmit
            if self._can_restore:
                self._window.pop()
            raise
        self._step_count += 1
        if _journal.ENABLED:
            # milestones count TRAINING steps, not calls — a K-superstep
            # step_fn advances K of them per call
            _journal.maybe_milestone(
                self._step_count * self.steps_per_call,
                source="supervisor")
        return self._check_divergence(out)

    __call__ = step

    # -- snapshot / restore --------------------------------------------------
    @property
    def _can_restore(self) -> bool:
        return (self._restore_fn is not None or self._pd is not None
                or self._trainer is not None)

    def _pack_live_state(self) -> dict:
        """The live training state in checkpoint-layer packing (the
        ``save_trainer`` key convention, so an emergency save of it is
        ``restore_trainer``-compatible)."""
        if self._snapshot_fn is not None:
            return self._snapshot_fn()
        from ..checkpoint.manager import PARAM_PREFIX, TRAINER_STATES_KEY
        state: dict = {}
        if self._pd is not None:
            state.update({f"{PARAM_PREFIX}{name}": p.data()
                          for name, p in self._pd.items()})
        if self._trainer is not None:
            state[TRAINER_STATES_KEY] = self._trainer.get_states_bytes()
        return state

    @property
    def _snapshot_calls(self) -> int:
        """Snapshot cadence in step_fn CALLS: ``snapshot_steps`` counts
        training steps, one call advances ``steps_per_call`` of them —
        under a K-superstep step_fn the boundary lands every
        ceil(snapshot_steps/K) calls, i.e. ON a superstep boundary."""
        return -(-self.snapshot_steps // self.steps_per_call)

    def _maybe_snapshot(self) -> None:
        due = self._snap is None \
            or self._step_count % self._snapshot_calls == 0
        if not due or not self._can_restore:
            return
        if self._snap is not None and self._snap[0] == self._step_count:
            return  # a retry re-entering the same boundary
        from .parameter import DeferredInitializationError
        try:
            snap = _layout.snapshot_state(self._pack_live_state())
        except DeferredInitializationError:
            # shapes materialize on the first forward; retry next step
            return
        self._snap = (self._step_count, snap)
        self._window.clear()
        if _metrics.ENABLED:
            _metrics.SUPERVISOR_SNAPSHOTS.inc()
            _metrics.SUPERVISOR_LAST_SNAPSHOT_STEP.set(self._step_count)

    def _restore_snapshot(self) -> None:
        assert self._snap is not None
        _, snap = self._snap
        state = {name: payload for name, (kind, payload) in snap.items()}
        if self._restore_fn is not None:
            self._restore_fn(state)
            return
        from ..checkpoint.manager import PARAM_PREFIX, TRAINER_STATES_KEY
        if self._pd is not None:
            for name, p in self._pd.items():
                arr = state.get(f"{PARAM_PREFIX}{name}")
                if arr is None:
                    raise MXNetError(
                        f"snapshot lacks parameter {name!r} — params "
                        "changed after the supervisor captured it")
                # same device-placement path restore_trainer uses: the
                # host copy becomes a FRESH device buffer, replacing
                # whatever a failed donated dispatch consumed.  A
                # sharded param re-commits to its NamedSharding here
                # too — _init_impl re-applies the recorded spec, so a
                # donation-safe retry restores the GSPMD placement, not
                # a single-device copy
                p._load_init(arr, p.list_ctx())
        if self._trainer is not None and TRAINER_STATES_KEY in state:
            self._trainer.set_states_bytes(state[TRAINER_STATES_KEY])

    # -- retry loop ----------------------------------------------------------
    def _attempt(self, args, kw):
        delay = self.backoff_s
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                if attempt:
                    time.sleep(delay)
                    delay *= 2
                    self._rewind_for_retry()
                return self._execute(args, kw)
            except (DivergenceError, TrainingStalledError):
                raise
            except BaseException as e:  # noqa: BLE001 — classify decides
                kind = _res.classify(e)
                if kind is not _res.TRANSIENT:
                    raise
                if not self._can_restore:
                    if not self._retry_warned:
                        log.warning(
                            "supervisor has no snapshot surface (no "
                            "trainer/params/restore_fn) — transient step "
                            "failures propagate instead of retrying")
                        self._retry_warned = True
                    raise
                last = e
                if attempt == self.retries:
                    raise StepRetriesExhausted(
                        f"step {self._step_count} failed "
                        f"{self.retries + 1} times on transient errors "
                        f"(last: {type(e).__name__}: {e})",
                        step=self._step_count) from e
                if _metrics.ENABLED:
                    _metrics.SUPERVISOR_RETRIES.inc()
                if _journal.ENABLED:
                    _journal.emit("supervisor_retry",
                                  step=self._step_count,
                                  attempt=attempt + 1,
                                  error=f"{type(e).__name__}: {e}")
                log.warning(
                    "supervisor: transient failure at step %d "
                    "(%s: %s) — restoring snapshot from step %s and "
                    "retrying (%d/%d)", self._step_count,
                    type(e).__name__, e,
                    self._snap[0] if self._snap else None,
                    attempt + 1, self.retries)
        raise StepRetriesExhausted(  # pragma: no cover — loop invariant
            f"step {self._step_count}", step=self._step_count) from last

    def _rewind_for_retry(self) -> None:
        """Restore the last snapshot and replay the batch window up to
        (but not including) the failed step — rebuilding every donated
        buffer from host copies, on the exact op sequence the
        uninterrupted run executed.  Replayed steps go through
        ``_execute`` too, so an injected fault landing mid-replay
        surfaces to ``_attempt`` and simply costs another retry."""
        if self._snap is None:
            # a transient on the FIRST step: the boundary capture was
            # skipped because params were still deferred-initialized,
            # but the failed attempt's build/trace materialized them
            # BEFORE the fault fired — so the live state is the state
            # the step started from, and capturing it NOW yields the
            # missing restore point.  If the state is unreadable (a
            # donated first dispatch already consumed the buffers),
            # snapshot_state raises and the original transient
            # propagates from _attempt.
            cur = self._window[-1] if self._window else None
            log.warning(
                "supervisor: first-step transient with no snapshot — "
                "capturing the post-attempt live state as the restore "
                "point.  This assumes the failed attempt mutated "
                "nothing (true for the wired fault sites, which fire "
                "pre-mutation, and for whole-step dispatch, whose "
                "donated buffers become unreadable on partial "
                "execution); a fused-path transient landing MID-update "
                "sequence would bake the partial state into the "
                "baseline")
            try:
                self._maybe_snapshot()  # clears the window on capture
            except Exception as e:  # noqa: BLE001 — deleted donated buffers
                raise MXNetError(
                    "supervisor cannot retry the first step: the live "
                    f"state is unreadable after the failed attempt ({e})"
                ) from e
            if self._snap is None:
                raise MXNetError(
                    "supervisor retry without a snapshot — parameters "
                    "are still deferred-initialized after the failed "
                    "attempt")
            if cur is not None and not self._window:
                # the in-flight step's batch must stay in the replay
                # window: the fresh snapshot predates it
                self._window.append(cur)
            return
        if _metrics.ENABLED:
            _metrics.SUPERVISOR_REWINDS.inc(reason="retry")
        # the restore + window replay is re-done work, not progress:
        # its whole wall-clock books as retry_replay badput, and any
        # trainer_step spans recorded inside are suppressed so replayed
        # steps don't double-count as goodput (docs/goodput.md)
        with _goodput.replay_scope("retry_replay"):
            self._restore_snapshot()
            for rargs, rkw in self._window[:-1]:
                self._execute(rargs, rkw)

    # -- stall-guarded execution ---------------------------------------------
    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        # SimpleQueue: C-implemented put/get — the per-step handoff is
        # the supervisor's main steady-state cost (the <=2% budget)
        self._work_q = _queue.SimpleQueue()
        self._result_q = _queue.SimpleQueue()
        self._worker = threading.Thread(
            target=self._worker_loop, name="mxt-supervisor-step",
            daemon=True)
        self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            job = self._work_q.get()
            if job is None:
                return
            fn, args, kw = job
            try:
                self._result_q.put(("ok", fn(*args, **kw)))
            except BaseException as e:  # noqa: BLE001 — carried to caller
                self._result_q.put(("err", e))

    def _stall_timeout(self) -> Optional[float]:
        """The current step deadline: ``stall_factor`` × the warmed
        EWMA, floored at ``stall_min_s``.  None (wait forever) until
        the supervisor's OWN measurements warm — this supervisor's
        first steps include compilation, which has no baseline, and a
        long-lived process's flight EWMA (warmed on a DIFFERENT
        trainer's steps) must not arm a deadline against them.  Once
        armed, the flight recorder's ``trainer_step``/``whole_step``
        watch EWMAs can only RAISE the deadline (they see the same
        steps plus whatever else shares the phase — the conservative
        direction)."""
        if self._ewma_n < _EWMA_WARMUP:
            return None
        ewma = self._ewma
        for phase in _STEP_PHASES:
            fe = _flight.watch_ewma(phase) if _flight.ENABLED else None
            if fe is not None and fe > ewma:
                ewma = fe
        return max(self.stall_min_s, self.stall_factor * ewma)

    def _execute(self, args, kw):
        t0 = time.perf_counter()
        self._in_step = True
        try:
            if self.stall_factor <= 0:
                # stall watchdog off: run inline — no worker thread, no
                # per-step context switches.  The hop costs a fixed
                # ~0.1-0.2 ms/step (two switches), invisible against
                # real accelerator steps but measurable against ms-scale
                # CPU ones; MXNET_SUPERVISE_STALL_FACTOR=0 is the
                # documented knob when that matters more than unhanging
                # a wedged device (retry + divergence still active)
                status, payload = "ok", self._step_fn(*args, **kw)
            else:
                self._ensure_worker()
                timeout = self._stall_timeout()
                self._work_q.put((self._step_fn, args, kw))
                try:
                    status, payload = self._result_q.get(timeout=timeout)
                except _queue.Empty:
                    self._on_stall(timeout)
        finally:
            self._in_step = False
        dur = time.perf_counter() - t0
        self._ewma = dur if self._ewma_n == 0 else \
            _EWMA_ALPHA * dur + (1.0 - _EWMA_ALPHA) * self._ewma
        self._ewma_n += 1
        if status == "err":
            raise payload
        return payload

    def _on_stall(self, timeout: float):
        self._stalled = (f"step {self._step_count} exceeded "
                         f"{timeout:.1f}s")
        if _metrics.ENABLED:
            _metrics.SUPERVISOR_WATCHDOG_TRIPS.inc(kind="stall")
        report = _res.post_mortem(
            "stall", step=self._step_count,
            detail={"timeout_s": round(timeout, 3),
                    "ewma_s": round(self._ewma, 6),
                    "stall_factor": self.stall_factor})
        if _goodput.ENABLED:
            # the wedged step never completes, so no span records it —
            # the watchdog's whole wait is the stall's badput
            _goodput.attribute("stall", timeout)
        if _journal.ENABLED:
            _journal.emit("supervisor_stall", step=self._step_count,
                          durable=True, timeout_s=round(timeout, 3),
                          report_path=(report or {}).get("report_path"),
                          flight_path=(report or {}).get("flight_path"))
        raise TrainingStalledError(
            f"training step {self._step_count} still running after "
            f"{timeout:.1f}s (EWMA {self._ewma * 1e3:.1f} ms x factor "
            f"{self.stall_factor:g}, floor {self.stall_min_s:g}s) — "
            "device presumed wedged; post-mortem "
            f"{(report or {}).get('report_path')}",
            step=self._step_count, timeout_s=timeout, report=report)

    # -- divergence watchdog -------------------------------------------------
    def _check_divergence(self, out):
        if self.check_every < 1 or \
                self._step_count % self.check_every != 0:
            return out
        if _finite(out):
            self._nonfinite = 0
            return out
        self._nonfinite += 1
        if self._nonfinite < self.diverge_patience:
            return out
        failing = self._step_count - 1  # the step just completed
        if _metrics.ENABLED:
            _metrics.SUPERVISOR_WATCHDOG_TRIPS.inc(kind="divergence")
        report = _res.post_mortem(
            "divergence", step=failing,
            detail={"consecutive_nonfinite": self._nonfinite,
                    "patience": self.diverge_patience})
        if _journal.ENABLED:
            _journal.emit("supervisor_divergence", step=failing,
                          durable=True, action=self.on_diverge,
                          report_path=(report or {}).get("report_path"),
                          flight_path=(report or {}).get("flight_path"))
        self._nonfinite = 0
        if self.on_diverge == "rewind" and self._snap is not None \
                and self._can_restore:
            if _metrics.ENABLED:
                _metrics.SUPERVISOR_REWINDS.inc(reason="divergence")
            log.warning(
                "supervisor: divergence at step %d — rewinding to the "
                "snapshot from step %d (MXNET_SUPERVISE_ON_DIVERGE="
                "rewind); post-mortem %s", failing, self._snap[0],
                (report or {}).get("report_path"))
            with _goodput.replay_scope("rewind"):
                self._restore_snapshot()
            # continuing FORWARD with fresh data from the snapshot
            # state: the window's batches produced the divergence, so
            # they are deliberately not replayed
            self._window.clear()
            return out
        raise DivergenceError(
            f"loss was nonfinite for {self.diverge_patience} consecutive "
            f"checked steps (last: step {failing}) — post-mortem "
            f"{(report or {}).get('report_path')}",
            step=failing, report=report)

    # -- preemption ----------------------------------------------------------
    def install_preemption_hook(self, manager, **kw) -> Callable[[], None]:
        """The PR 5 SIGTERM hook, fired through the supervisor: the
        emergency save uses the last rolling host snapshot when the
        signal lands MID-STEP (live device buffers may be half-updated
        or donated at that instant) and a fresh consistent pack
        otherwise.  State is saved in ``save_trainer`` key packing, so
        ``restore_trainer``/``restore_or_initialize`` resume it.  The
        hook also dumps the flight ring (``reason="preempt"``) — see
        checkpoint/hooks.py.  Returns the uninstaller."""
        from ..checkpoint.hooks import install_preemption_hook

        def state_fn():
            if self._in_step and self._snap is not None:
                step, snap = self._snap
                return step, {name: payload
                              for name, (kind, payload) in snap.items()}
            if self._in_step:
                log.warning("preemption landed mid-step with no snapshot "
                            "yet — saving live state (may be mid-update)")
            return self._step_count, self._pack_live_state()

        return install_preemption_hook(manager, state_fn, **kw)

    # -- lifecycle -----------------------------------------------------------
    @property
    def stalled(self) -> Optional[str]:
        """Poison reason after a stall (None = healthy)."""
        return self._stalled

    def stats(self) -> dict:
        return {
            "enabled": ENABLED,
            "steps": self._step_count,
            "snapshot_step": self._snap[0] if self._snap else None,
            "window": len(self._window),
            "nonfinite_streak": self._nonfinite,
            "stalled": self._stalled,
            "ewma_ms": round(self._ewma * 1e3, 3),
        }

    def close(self) -> None:
        """Stop the step executor thread (idempotent).  A poisoned
        (stalled) supervisor's worker is left behind on purpose — it is
        blocked inside the wedged dispatch."""
        w, q = self._worker, self._work_q
        self._worker = None
        if w is None or not w.is_alive():
            return
        if self._stalled is None and q is not None:
            q.put(None)
            w.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
