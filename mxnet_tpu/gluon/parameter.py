"""gluon.Parameter / ParameterDict (parity: python/mxnet/gluon/parameter.py:43,462).

Deferred initialization, grad_req plumbing, save/load.  TPU note: a
Parameter owns ONE buffer; multi-device replication/sharding is a placement
property handled by the Trainer/mesh (SPMD), not N copies as in the
reference's per-GPU lists — list_data() returns the single (possibly
mesh-sharded) array for API parity.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import Context, cpu, current_context
from ..observability import memory as _memory
from ..observability.memory import memory_scope as _memory_scope
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import initializer
from ..initializer import InitDesc


class DeferredInitializationError(MXNetError):
    pass


_zero_all_fn = None


def _zero_all(arrs):
    """One compiled program producing zeros for every buffer (jax caches
    per shape/dtype signature)."""
    global _zero_all_fn
    if _zero_all_fn is None:
        import jax
        import jax.numpy as jnp
        _zero_all_fn = jax.jit(lambda xs: [jnp.zeros_like(x) for x in xs])
    return _zero_all_fn(arrs)


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._ctx = None
        self._deferred_init = ()
        # GSPMD placement (ISSUE 18): the NamedSharding this parameter's
        # buffer is committed to, plus the (mesh, PartitionSpec) pair it
        # was derived from.  A placement PROPERTY, not data: _init_impl
        # re-applies it on every load path (checkpoint restore,
        # supervisor snapshot restore, deferred init), so a sharded
        # param stays sharded through every restore the last 8 PRs built
        self._sharding = None
        self._sharding_spec = None
        self._sharding_mesh = None
        self.name = name
        self._grad_req = None
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req if differentiable else "null"
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        # row_sparse grad_stype: the grad buffer IS a RowSparseNDArray
        # (rows-only); autograd deposits token rows into it and the
        # optimizer/kvstore stay on the O(nnz) lazy path
        # (parity: gluon sparse embeddings, optimizer_op.cc rsp kernels)
        self._grad_stype = grad_stype

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not getattr(self, "_differentiable", True):
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null" and self._data is not None:
            self._grad = None
            self._data._grad = None
        elif self._data is not None:
            self._init_grad()

    def _check_and_get(self, arr, ctx):
        if arr is not None:
            return arr
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter {self.name} has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass.")
        raise RuntimeError(
            f"Parameter {self.name} has not been initialized. You should "
            "initialize parameters with Block.collect_params().initialize()")

    def _load_init(self, data, ctx):
        if self.shape and _np.prod(self.shape) > 0:
            for self_dim, data_dim in zip(self.shape, data.shape):
                if self_dim not in (0, data_dim):
                    raise AssertionError(
                        f"Failed loading Parameter {self.name}: shape mismatch "
                        f"{self.shape} vs {data.shape}")
        self.shape = tuple(data.shape)
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._deferred_init = ()
        self._init_impl(data, ctx)

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if self.shape is None or _np.prod(self.shape) <= 0:
            raise MXNetError(
                f"Cannot initialize Parameter {self.name} because it has "
                "invalid shape: {self.shape}.")
        if data is None:
            # HBM ledger: the parameter buffer is born here — tag it.
            # ``_memory_tag`` (default "param") lets a subsystem claim
            # its own ledger row: ShardedEmbedding stamps "embed_shards"
            # so ensure_headroom / the registry cost model see table
            # bytes as their own class (docs/memory.md taxonomy)
            with _memory_scope(getattr(self, "_memory_tag", "param")):
                data = nd.zeros(self.shape, dtype=self.dtype, ctx=ctx[0])
                initializer.create(default_init)(
                    InitDesc(self.name, {"__init__": init}), data)
        self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx = list(ctx_list)
        with _memory_scope(getattr(self, "_memory_tag", "param")):
            if not isinstance(data, NDArray):
                data = nd.array(data, dtype=self.dtype)
            self._data = data.as_in_context(self._ctx[0]) if \
                data.context != self._ctx[0] else data
            if self._sharding is not None:
                # re-commit to the mesh placement: this is THE point
                # every load path funnels through (_load_init from
                # checkpoint restore, the supervisor's donation-safe
                # snapshot restore, deferred init), so a restored host
                # copy lands back as the same sharded device array a
                # failed donated dispatch consumed
                self._apply_sharding_locked()
            if _memory.ENABLED:
                # load-path wrappers (ParameterDict.load / _load_init)
                # arrive already registered under their creation tag
                # (nd.load -> _untagged); re-registering retags the
                # same live wrapper to param instead of double counting
                _memory.register_nd(self._data)
        self._init_grad()

    # -- GSPMD sharding (ISSUE 18) ------------------------------------------
    def _apply_sharding_locked(self):
        """device_put the live buffer onto its NamedSharding (committed
        placement — jax.jit then treats the spec as an in_sharding and
        inserts the collectives).  Caller holds the param-tag scope."""
        import jax
        # mesh placement of the param's own buffer — a retag of the same
        # logical allocation, not a new one
        self._data._set_data(
            jax.device_put(self._data._data, self._sharding))  # graft-lint: disable=memory-hygiene

    def __getstate__(self):
        """The live NamedSharding/Mesh hold Device handles that cannot
        cross a pickle boundary (Updater.get_states packs the optimizer
        whose param_dict points back here).  Drop them — the spec
        string survives, and the next whole-step bind re-resolves the
        mesh and re-commits the placement in the new process."""
        state = self.__dict__.copy()
        state["_sharding"] = None
        state["_sharding_mesh"] = None
        return state

    @property
    def sharding_spec(self):
        """The PartitionSpec this parameter is annotated with (None =
        replicated / never sharded)."""
        return self._sharding_spec

    @property
    def sharding(self):
        """The committed NamedSharding, or None."""
        return self._sharding

    def set_sharding(self, mesh, spec) -> None:
        """Annotate this parameter with a GSPMD placement: ``spec`` is a
        ``jax.sharding.PartitionSpec`` (or axis-name tuple) over
        ``mesh``.  Applies immediately when the buffer exists and
        re-applies on every restore path (``_init_impl``).  ``mesh=None``
        clears the annotation (the buffer keeps its current placement
        until the next restore)."""
        if mesh is None:
            self._sharding = None
            self._sharding_spec = None
            self._sharding_mesh = None
            return
        from jax.sharding import NamedSharding, PartitionSpec
        if not isinstance(spec, PartitionSpec):
            spec = PartitionSpec(*spec) if spec else PartitionSpec()
        self._sharding_mesh = mesh
        self._sharding_spec = spec
        self._sharding = NamedSharding(mesh, spec)
        if self._data is not None:
            with _memory_scope(getattr(self, "_memory_tag", "param")):
                self._apply_sharding_locked()
            from ..ndarray.sparse import RowSparseNDArray
            if self._grad is not None and \
                    not isinstance(self._grad, RowSparseNDArray):
                # keep the grad buffer's placement consistent with the
                # data it shadows (the eager fallback path deposits into
                # it; mismatched placements would force XLA reshards)
                import jax
                self._grad._set_data(
                    jax.device_put(self._grad._data, self._sharding))  # graft-lint: disable=memory-hygiene

    def _init_grad(self):
        if self.grad_req == "null":
            self._grad = None
            return
        if self._grad_stype == "row_sparse":
            # rows-only gradient buffer: autograd deposits (ids, rows)
            # directly — O(vocab) dense grads are never allocated
            # (parity: rsp embedding grads, optimizer_op.cc rsp kernels)
            from ..ndarray import sparse as _sp
            with _memory_scope("grad"):
                self._grad = _sp.zeros_sparse(
                    "row_sparse", self._data.shape,
                    ctx=self._data.context, dtype=self._data.dtype)
        else:
            with _memory_scope("grad"):
                self._grad = nd.zeros(self._data.shape,
                                      dtype=self._data.dtype,
                                      ctx=self._data.context)
        from .. import autograd
        autograd.mark_variables([self._data], [self._grad], self.grad_req)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if self.shape is None or _np.prod([d for d in self.shape]) <= 0 \
                or any(d == 0 for d in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise MXNetError(f"Cannot initialize Parameter {self.name} "
                             "because it has invalid shape.")
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            self._ctx = ctx
            self._init_grad()
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(f"Cannot reset context for Parameter {self.name} "
                             "because it has not been initialized.")

    def set_data(self, data):
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter {self.name} has not been initialized"
            init, ctx, default_init, _ = self._deferred_init
            self.shape = tuple(data.shape)
            self._deferred_init = (init, ctx, default_init, data)
            self._finish_deferred_init()
            return
        self._data._set_data(
            (data._data if isinstance(data, NDArray) else nd.array(data)._data
             ).astype(self._data.dtype))

    def data(self, ctx=None) -> NDArray:
        arr = self._check_and_get(self._data, ctx)
        return arr

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None) -> NDArray:
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter {self.name} "
                "because grad_req='null'")
        return self._check_and_get(self._grad, ctx)

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError(f"Parameter {self.name} has not been initialized")
        return self._ctx

    def zero_grad(self):
        if self._grad is None:
            return
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(self._grad, RowSparseNDArray):
            self._grad._clear_rows()
        else:
            self._grad[:] = 0

    @property
    def fresh_grad(self):
        """True when backward has deposited into this parameter's grad on
        any device copy since the last Trainer step (the stale-grad
        guard's source of truth; parity: NDArray::fresh_out_grad)."""
        return self._data is not None and \
            any(getattr(d, "_fresh_grad", False) for d in self.list_data())

    def var(self):
        from .. import symbol
        if self._var is None:
            self._var = symbol.Variable(self.name, shape=self.shape,
                                        dtype=self.dtype, lr_mult=self.lr_mult,
                                        wd_mult=self.wd_mult,
                                        init=self.init)
        return self._var

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is None:
            return
        self._data = self._data.astype(self.dtype)
        if self._grad is not None:
            self._grad = self._grad.astype(self.dtype)
            from .. import autograd
            autograd.mark_variables([self._data], [self._grad], self.grad_req)


class Constant(Parameter):
    """Constant parameter (grad_req null, init from value)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _Init(initializer.Initializer):
            def _init_weight(self, _, arr):
                value.copyto(arr)

            _init_default = _init_weight

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_Init())


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "\n".join(repr(v) for v in self.values())
        return f"ParameterDict '{self._prefix}' (\n{s}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __len__(self):
        return len(self._params)

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs) -> Parameter:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if getattr(param, k, None) is not None and v is not None:
                    existing = getattr(param, k)
                    if k == "shape" and len(v) == len(existing):
                        inferred = tuple(vi if vi != 0 else ei
                                         for vi, ei in zip(v, existing))
                        param.shape = inferred
                        continue
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'.")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    f"Cannot update because duplicate Parameter '{k}'"
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        """Zero every dense grad buffer in ONE jitted dispatch (the
        per-parameter loop issued O(#params) device ops); row-sparse
        grads clear their rows host-side as before."""
        from ..ndarray.sparse import RowSparseNDArray
        dense = []
        for p in self.values():
            g = p._grad
            if g is None:
                continue
            if isinstance(g, RowSparseNDArray):
                g._clear_rows()
            else:
                dense.append(g)
        if not dense:
            return
        for g, z in zip(dense, _zero_all([g._data for g in dense])):
            g._set_data(z)

    def reset_ctx(self, ctx):
        for i in self.values():
            i.reset_ctx(ctx)

    def setattr(self, name, value):
        for i in self.values():
            setattr(i, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise ValueError(f"Prefix '{strip_prefix}' is to be stripped "
                                 f"but Parameter's name '{param.name}' does "
                                 "not start with it")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = nd.load(filename)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter {name} is missing in file {filename}"
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    f"Parameter {name} loaded from {filename} is not present " \
                    "in ParameterDict"
                continue
            self[name]._load_init(arg_dict[name], ctx or [cpu()])
