"""gluon.utils (parity: python/mxnet/gluon/utils.py): split_and_load,
clip_global_norm, check_sha1, download (gated: zero-egress environments)."""
from __future__ import annotations

import hashlib
import math
import os

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            f"Too many slices for data with shape {data.shape}. Arguments are "
            f"num_slice={num_slice} and batch_axis={batch_axis}.")
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}.")
    step = size // num_slice
    if batch_axis == 0:
        slices = [data[i * step:(i + 1) * step] if i < num_slice - 1 else
                  data[i * step:size] for i in range(num_slice)]
    else:
        slices = [nd.slice_axis(data, batch_axis, i * step, (i + 1) * step)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so that the sum of their 2-norms is <= max_norm."""
    assert len(arrays) > 0
    total_norm = 0.0
    for arr in arrays:
        total_norm += float((arr.reshape((-1,)) ** 2).sum().asscalar())
    total_norm = math.sqrt(total_norm)
    if math.isnan(total_norm) or math.isinf(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Download a file (requires network; raises in zero-egress environments
    with a pointer to pre-staged files)."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if not overwrite and os.path.exists(fname) and (
            not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    try:
        import urllib.request
        print(f"Downloading {fname} from {url}...")
        urllib.request.urlretrieve(url, fname)
    except Exception as e:
        raise MXNetError(
            f"download of {url} failed ({e}); in offline environments stage "
            f"the file at {fname} manually") from None
    if sha1_hash and not check_sha1(fname, sha1_hash):
        raise UserWarning(f"File {fname} is downloaded but the content hash "
                          "does not match.")
    return fname
