"""gluon.rnn fused layers RNN/LSTM/GRU (parity: gluon/rnn/rnn_layer.py:31-428).

The reference used the fused cuDNN RNN op on GPU and fell back to unrolled
cells on CPU (rnn.cc:33 is GPU-only).  Here the fused `RNN` operator is a
`lax.scan` (ops/sequence.py) that compiles for TPU *and* CPU, so the fused
path is always taken.  Per-layer parameters keep the reference's naming
(l0_i2h_weight, ...) and are packed into the cuDNN flat layout at forward.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from . import rnn_cell


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout '{layout}'; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                     h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = f"{shape[1] if shape[1] else None} -> {shape[0] // self._gates}"
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _collect_params_with_prefix(self, prefix=""):
        return {prefix + name: p for name, p in self._reg_params.items()}

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(name=f"{self.prefix}h0_{i}", **info))
        return states

    def _unfuse(self):
        """Unfuse into stacked cells (parity: rnn_layer._unfuse)."""
        get_cell = {
            "rnn_relu": lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation="relu", **kw),
            "rnn_tanh": lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation="tanh", **kw),
            "lstm": lambda **kw: rnn_cell.LSTMCell(self._hidden_size, **kw),
            "gru": lambda **kw: rnn_cell.GRUCell(self._hidden_size, **kw),
        }[self._mode]
        stack = rnn_cell.SequentialRNNCell(prefix=self.prefix,
                                           params=self.params)
        with stack.name_scope():
            ni = self._input_size
            for i in range(self._num_layers):
                kwargs = {"input_size": ni,
                          "i2h_weight_initializer": self._i2h_weight_initializer,
                          "h2h_weight_initializer": self._h2h_weight_initializer,
                          "i2h_bias_initializer": self._i2h_bias_initializer,
                          "h2h_bias_initializer": self._h2h_bias_initializer}
                if self._dir == 2:
                    stack.add(rnn_cell.BidirectionalCell(
                        get_cell(prefix=f"l{i}_", **kwargs),
                        get_cell(prefix=f"r{i}_", **kwargs)))
                else:
                    stack.add(get_cell(prefix=f"l{i}_", **kwargs))
                if self._dropout > 0 and i != self._num_layers - 1:
                    stack.add(rnn_cell.DropoutCell(self._dropout))
                ni = self._hidden_size * self._dir
        return stack

    def forward(self, inputs, states=None):
        from ...ndarray import NDArray
        from ...symbol import Symbol
        from ... import ndarray as F
        if isinstance(inputs, Symbol) or self._active:
            # symbol composition / hybridized CachedOp: the whole layer is
            # one RNN op node (use_default_state builds zero states inside
            # the op, so no shape access is needed here)
            if states is None:
                return super().forward(inputs)
            if isinstance(states, NDArray):
                states = [states]
            if isinstance(inputs, NDArray):
                # same recurrent-state validation as the eager path — a
                # transposed state with matching element count would
                # otherwise reshape silently into wrong numbers
                bs = inputs.shape[self._layout.find("N")]
                for state, info in zip(states, self.state_info(bs)):
                    if state.shape != info["shape"]:
                        raise ValueError(
                            f"Invalid recurrent state shape. Expecting "
                            f"{info['shape']}, got {state.shape}.")
            return super().forward(inputs, *states)
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.context)
        if isinstance(states, NDArray):
            states = [states]
        for state, info in zip(states, self.state_info(batch_size)):
            if state.shape != info["shape"]:
                raise ValueError(
                    f"Invalid recurrent state shape. Expecting {info['shape']}, "
                    f"got {state.shape}.")
        if self._input_size == 0:
            for i in (["l", "r"] if self._dir == 2 else ["l"]):
                p = getattr(self, f"{i}0_i2h_weight")
                p.shape = (self._gates * self._hidden_size,
                           inputs.shape[2])
                p._finish_deferred_init()
        out = self._forward_kernel(inputs, states)
        return out[0] if skip_states else out

    def hybrid_forward(self, F, inputs, *states, **params):
        """Symbol-composable kernel: params packed with F ops, states
        optional (the RNN op's use_default_state builds zeros on-device,
        where shapes are concrete)."""
        dirs = ["l", "r"] if self._dir == 2 else ["l"]
        parts = []
        for i in range(self._num_layers):
            for j in dirs:
                parts.append(F.Reshape(params[f"{j}{i}_i2h_weight"],
                                       shape=(-1,)))
                parts.append(F.Reshape(params[f"{j}{i}_h2h_weight"],
                                       shape=(-1,)))
        for i in range(self._num_layers):
            for j in dirs:
                parts.append(params[f"{j}{i}_i2h_bias"])
                parts.append(params[f"{j}{i}_h2h_bias"])
        packed = F.Concat(*parts, dim=0)
        x = F.SwapAxis(inputs, dim1=0, dim2=1) if self._layout == "NTC" \
            else inputs
        rnn = F.RNN(x, packed, *states, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True, mode=self._mode,
                    use_default_state=not states)
        out = rnn[0]
        if self._layout == "NTC":
            out = F.SwapAxis(out, dim1=0, dim2=1)
        if not states:
            return out
        new_states = [rnn[1]]
        if self._mode == "lstm":
            new_states.append(rnn[2])
        return out, new_states

    def _forward_kernel(self, inputs, states):
        """Eager kernel = hybrid_forward with F=nd (ONE packing recipe for
        both paths — they cannot drift)."""
        from ... import ndarray as F
        ctx = inputs.context
        params = {name: p.data(ctx)
                  for name, p in self._reg_params.items()}
        return self.hybrid_forward(F, inputs, *states, **params)


class RNN(_RNNLayer):
    """Parity: gluon.rnn.RNN."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Parity: gluon.rnn.LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Parity: gluon.rnn.GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
