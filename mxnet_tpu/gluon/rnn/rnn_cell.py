"""gluon.rnn cells (parity: python/mxnet/gluon/rnn/rnn_cell.py).

RecurrentCell, RNNCell, LSTMCell, GRUCell, SequentialRNNCell, DropoutCell,
BidirectionalCell, ResidualCell, ZoneoutCell — unrolled step-by-step; the
fused counterpart is gluon.rnn.rnn_layer (lax.scan RNN op).
"""
from __future__ import annotations

from ..block import HybridBlock
from ..parameter import Parameter


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    from ...ndarray import NDArray
    from ... import ndarray as F_nd
    from ...symbol import Symbol
    from ... import symbol as F_sym
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    if isinstance(inputs, (Symbol, NDArray)):
        F = F_sym if isinstance(inputs, Symbol) else F_nd
        if isinstance(inputs, NDArray):
            batch_size = inputs.shape[batch_axis]
            if length is None:
                length = inputs.shape[axis]
        if merge is False:
            inputs = list(F.SliceChannel(inputs, axis=axis, num_outputs=length,
                                         squeeze_axis=True))
    else:
        assert length is None or len(inputs) == length
        F = F_sym if isinstance(inputs[0], Symbol) else F_nd
        if isinstance(inputs[0], NDArray):
            batch_size = inputs[0].shape[batch_axis - (batch_axis > axis)] \
                if inputs[0].ndim < 3 else inputs[0].shape[batch_axis]
            if inputs[0].ndim == 2:
                batch_size = inputs[0].shape[0]
        if merge is True:
            inputs = F.Concat(*[F.expand_dims(i, axis=axis) for i in inputs],
                              dim=axis)
    return inputs, axis, F, batch_size


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children:
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        assert not self._modified
        states = []
        func = func or F.zeros
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name=f"{self._prefix}begin_state_{self._init_counter}",
                         **info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(length, inputs, layout,
                                                       False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            # each sample's returned state is the one at its last valid
            # step (parity: unroll's F.SequenceLast over per-step states),
            # and outputs past valid_length are zero-masked
            states = [F.SequenceLast(
                F.Concat(*[F.expand_dims(s[i], axis=0) for s in all_states],
                         dim=0),
                valid_length, use_sequence_length=True, axis=0)
                for i in range(len(states))]
            merged = F.Concat(*[F.expand_dims(o, axis=axis)
                                for o in outputs], dim=axis)
            merged = F.SequenceMask(merged, valid_length,
                                    use_sequence_length=True, axis=axis)
            if merge_outputs:
                return merged, states
            outputs = list(F.SliceChannel(merged, num_outputs=length,
                                          axis=axis, squeeze_axis=True))
            return outputs, states
        if merge_outputs:
            outputs = F.Concat(*[F.expand_dims(o, axis=axis) for o in outputs],
                               dim=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell):
    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size, name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size, name=prefix + "h2h")
        output = self._get_activation(F, i2h + h2h, self._activation,
                                      name=prefix + "out")
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4, axis=1,
                                     name=prefix + "slice")
        in_gate = F.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = F.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = F.Activation(slice_gates[2], act_type="tanh")
        out_gate = F.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "h2h")
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=1,
                                             name=prefix + "i2h_slice")
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=1,
                                             name=prefix + "h2h_slice")
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset_gate * h2h_n, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, (int, float))
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate,
                               name=f"t{self._counter}_fwd")
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        assert not base_cell._modified
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ResidualCell(ModifierCell):
    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        states = ([F.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. Please "
                                  "use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(length, inputs, layout,
                                                       False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size=batch_size)
        states = begin_state
        l_cell, r_cell = self._children

        def _rev(seq):
            """Time-reverse a list of per-step (N, C) frames; with
            valid_length, reverse only within each sample's valid span
            (SequenceReverse semantics — padded tail stays in place)."""
            if valid_length is None:
                return list(reversed(seq))
            stacked = F.Concat(*[F.expand_dims(o, axis=0) for o in seq],
                               dim=0)  # TNC
            rev = F.SequenceReverse(stacked, valid_length,
                                    use_sequence_length=True)
            return list(F.SliceChannel(rev, num_outputs=length, axis=0,
                                       squeeze_axis=True))

        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=_rev(inputs),
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        outputs = [F.Concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, _rev(r_outputs))]
        if merge_outputs:
            outputs = F.Concat(*[F.expand_dims(o, axis=axis)
                                 for o in outputs], dim=axis)
        states = l_states + r_states
        return outputs, states

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError
