"""gluon.rnn namespace (parity: python/mxnet/gluon/rnn/__init__.py)."""
from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell, LSTMCell,
                       GRUCell, SequentialRNNCell, DropoutCell, ModifierCell,
                       ResidualCell, ZoneoutCell, BidirectionalCell)
from .rnn_layer import RNN, LSTM, GRU
