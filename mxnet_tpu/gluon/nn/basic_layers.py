"""gluon.nn basic layers (parity: python/mxnet/gluon/nn/basic_layers.py:32-460).

Sequential, HybridSequential, Dense, Activation, Dropout, BatchNorm,
LeakyReLU, Embedding, Flatten, Lambda, HybridLambda, InstanceNorm, LayerNorm.
"""
from __future__ import annotations

import numpy as _np

from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({i}): {repr(b)}"
                           for i, b in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.")
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Parity: nn.Dense → FullyConnected."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get("weight", shape=(units, in_units),
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(units,),
                                            init=bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            act = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten, name="fwd")
        else:
            act = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   flatten=self._flatten, name="fwd")
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return f"{self.__class__.__name__}({shape[0]} -> " \
               f"{shape[1] if len(shape) > 1 else None}, " \
               f"{'linear' if self.act is None else self.act})"


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type, name="fwd")

    def __repr__(self):
        return f"{self.__class__.__name__}({self._act_type})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes, name="fwd")

    def __repr__(self):
        return f"{self.__class__.__name__}(p = {self._rate})"


class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        if in_channels != 0:
            self.in_channels = in_channels
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True,
                                     differentiable=scale)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True,
                                    differentiable=center)
        self.running_mean = self.params.get("running_mean", grad_req="null",
                                            shape=(in_channels,),
                                            init=running_mean_initializer,
                                            allow_deferred_init=True,
                                            differentiable=False)
        self.running_var = self.params.get("running_var", grad_req="null",
                                           shape=(in_channels,),
                                           init=running_variance_initializer,
                                           allow_deferred_init=True,
                                           differentiable=False)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out = F.BatchNorm(x, gamma, beta, running_mean, running_var,
                          name="fwd", **self._kwargs)
        if isinstance(out, (list, tuple)):
            return out[0]
        return out

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return f"{self.__class__.__name__}(axis={self._kwargs['axis']}, " \
               f"eps={self._kwargs['eps']}, " \
               f"momentum={self._kwargs['momentum']}, " \
               f"in_channels={in_channels if in_channels else None})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha, name="fwd")

    def __repr__(self):
        return f"{self.__class__.__name__}({self._alpha})"


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      init=weight_initializer,
                                      allow_deferred_init=True,
                                      grad_stype="row_sparse" if sparse_grad
                                      else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        return "{block_name}({input_dim} -> {output_dim}, {dtype})".format(
            block_name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd", eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, name="fwd",
                              eps=self._epsilon).swapaxes(1, self._axis)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon, "axis": axis}
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        out = F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)
        if isinstance(out, (list, tuple)):
            return out[0]
        return out


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            assert hasattr(F, function), f"Function name {function} is not " \
                "found in ndarray."
            self._func_impl = getattr(F, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")
        self._func_name = getattr(self._func_impl, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray, symbol
            assert hasattr(ndarray, function) and hasattr(symbol, function), \
                f"Function name {function} is not found in ndarray/symbol."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


class MultiHeadAttention(HybridBlock):
    """Multi-head self-attention over the Pallas flash kernel.

    New TPU-first capability (the 2017 reference predates attention): the
    score matrix never materializes (ops/flash_attention.py), so sequence
    length is bounded by HBM activations, not O(T^2) scores; shard the
    sequence with parallel.sequence_parallel for multi-chip contexts.

    Inputs (N, T, E); `units` must divide by `num_heads`.
    """

    def __init__(self, units, num_heads, causal=False, use_bias=True,
                 weight_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._heads = num_heads
        self._causal = causal
        with self.name_scope():
            self.qkv = Dense(3 * units, flatten=False, use_bias=use_bias,
                             weight_initializer=weight_initializer,
                             prefix="qkv_")
            self.out_proj = Dense(units, flatten=False, use_bias=use_bias,
                                  weight_initializer=weight_initializer,
                                  prefix="out_")

    def hybrid_forward(self, F, x):
        H = self._heads
        Dh = self._units // H
        qkv = self.qkv(x)                                   # (N, T, 3E)
        qkv = F.reshape(qkv, shape=(0, 0, 3 * H, Dh))
        qkv = F.transpose(qkv, axes=(0, 2, 1, 3))           # (N, 3H, T, Dh)
        q = F.slice_axis(qkv, axis=1, begin=0, end=H)
        k = F.slice_axis(qkv, axis=1, begin=H, end=2 * H)
        v = F.slice_axis(qkv, axis=1, begin=2 * H, end=3 * H)
        o = F.flash_attention(q, k, v, causal=self._causal)  # (N, H, T, Dh)
        o = F.transpose(o, axes=(0, 2, 1, 3))
        o = F.reshape(o, shape=(0, 0, -1))                   # (N, T, E)
        return self.out_proj(o)
