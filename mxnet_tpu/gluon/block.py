"""gluon Block / HybridBlock / SymbolBlock (parity: python/mxnet/gluon/block.py).

hybridize() parity with the TPU twist: `_build_cache` traces hybrid_forward
with Symbol placeholders into a graph (block.py:381-384 in the reference) and
compiles it whole through `jax.jit` (the CachedOp below) — XLA fuses the
entire block into one executable, the reason hybridize exists.  Eager mode
runs the same hybrid_forward with `F = mx.nd` and records on the autograd
tape.
"""
from __future__ import annotations

import copy
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, getenv
from ..context import cpu
from ..observability import introspect as _introspect
from ..observability import metrics as _metrics
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import symbol as sym_mod
from ..symbol import Symbol
from ..symbol.graph import GraphPlan, infer_shapes_types
from .. import autograd
from .parameter import Parameter, ParameterDict, DeferredInitializationError


class _BlockScope:
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from ..name import NameManager
                prefix = NameManager.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        from ..name import NameManager, Prefix
        self._name_scope = Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


def _flatten(args, inout_str="input"):
    if isinstance(args, NDArray) or isinstance(args, Symbol):
        return [args], int(0)
    if args is None:
        return [None], None
    assert isinstance(args, (list, tuple)), \
        f"{inout_str} must be (nested) list of Symbol or NDArray, got {args}"
    flat = []
    fmts = []
    for i in args:
        arg, fmt = _flatten(i, inout_str)
        flat.extend(arg)
        fmts.append(fmt)
    return flat, fmts


def _regroup(args, fmt):
    if isinstance(fmt, int):
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    if fmt is None:
        return None, args[1:]
    assert isinstance(fmt, (list, tuple))
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block:
    """Base building block (parity: gluon/block.py:121)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: List["Block"] = []

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({i}): {repr(b)}"
                           for i, b in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.register_child(value)
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            import re
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children:
            ret.update(cld.collect_params(select=select))
        return ret

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing, ignore_extra,
                                   self.prefix)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer
        self.collect_params().initialize(init or initializer.Uniform(),
                                         ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for cld in self._children:
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def shard(self, mesh=None, spec_fn=None):
        """Annotate every parameter of this block (children included)
        with a ``NamedSharding`` on ``mesh`` (default: the ambient
        ``parallel.mesh.current_mesh()``).  ``spec_fn(name, param)``
        may return a ``PartitionSpec`` per parameter (None = keep the
        default rule: trainable >=2-D tensors shard their largest
        evenly-divisible dim along the model axis, everything else
        replicates).  Initialized params re-place immediately;
        uninitialized ones place at init — either way the whole-step
        compiler sees committed shardings and jit inserts the
        collectives.  Returns ``self`` for chaining."""
        from ..parallel import mesh as _pmesh
        mesh = _pmesh.resolve_mesh(mesh)
        if mesh is None:
            raise ValueError(
                "Block.shard() needs a mesh: pass one, or install an "
                "ambient mesh via parallel.mesh.set_current_mesh / "
                "use_mesh / MXNET_MESH_BATCH/MXNET_MESH_MODEL")
        for name, p in self.collect_params().items():
            spec = spec_fn(name, p) if spec_fn is not None else None
            if spec is None:
                shape = tuple(p.shape) if p.shape is not None else ()
                if not shape or any(d <= 0 for d in shape):
                    # deferred-init shape: leave the spec unset so the
                    # whole-step bind (or a re-shard after init)
                    # computes the default from the REAL shape
                    continue
                spec = _pmesh.default_param_spec(
                    mesh, shape, trainable=p.grad_req != "null")
            p.set_sharding(mesh, spec)
        return self

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


class CachedOp:
    """Compiled graph closure (parity: Imperative::CachedOp,
    src/imperative/cached_op.cc).

    Both directions are jitted: forward is one XLA executable; the backward
    stored on the autograd tape is a second executable computing the vjp
    (forward recomputed inside the compiled program, fused by XLA) — the
    TPU analog of CachedOp's cached forward/backward graphs
    (cached_op.cc:179,227).
    """

    def __init__(self, symbol: Symbol):
        self.symbol = symbol
        self.plan = GraphPlan(symbol)
        self._fwd = jax.jit(
            lambda args, aux, key, t: self.plan.run(args, aux, key, t),
            static_argnums=(3,))
        self._bwd_cache = {}
        self._fwd_donated = None  # built on first donated inference call
        self._noted = set()  # introspection captures done (fwd/bwd)

    def _get_fwd_donated(self):
        """Inference-mode forward that DONATES the non-parameter inputs
        (MXNET_DONATE_INFER): the data buffer's HBM block is released to
        the program instead of held live across the call — the serving
        path's donated-buffer dispatch, available to hybridized blocks.
        Params/aux ride a separate non-donated slot, so weights survive.
        Caveat (docs/inference.md): on backends with real donation the
        caller's input NDArray is consumed by the call."""
        if self._fwd_donated is None:
            plan = self.plan

            def fwd_d(data_vals, param_vals, aux_vals, key, t):
                merged = dict(param_vals)
                merged.update(data_vals)
                return plan.run(merged, aux_vals, key, t)

            # one-time, narrowly-scoped filter install (NOT a per-call
            # warnings.catch_warnings, which mutates process-global
            # filter state non-thread-safely on every forward): backends
            # without usable donation warn at each retrace; the user
            # opted into best-effort donation, so that specific warning
            # is expected noise
            import warnings as _warnings
            _warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            self._fwd_donated = jax.jit(
                fwd_d, static_argnums=(4,), donate_argnums=(0,))
        return self._fwd_donated

    def _run_all(self, names, vals_list, aux_vals, key, is_train):
        d = dict(zip(names, vals_list))
        outs, new_aux = self.plan.run(d, aux_vals, key, is_train)
        return tuple(outs) + tuple(new_aux[k] for k in sorted(new_aux))

    def _get_bwd(self, names):
        key_ = tuple(names)
        if key_ not in self._bwd_cache:
            plan = self.plan

            def bwd(primals, cots, aux_vals, key, is_train):
                def run(*vals):
                    d = dict(zip(key_, vals))
                    outs, new_aux = plan.run(d, aux_vals, key, is_train)
                    return tuple(outs) + tuple(new_aux[k] for k in sorted(new_aux))

                _, vjp_fn = jax.vjp(run, *primals)
                return vjp_fn(cots)

            self._bwd_cache[key_] = jax.jit(bwd, static_argnums=(4,))
        return self._bwd_cache[key_]

    def __call__(self, arg_arrays: Dict[str, NDArray],
                 aux_arrays: Dict[str, NDArray], ctx, input_names=None):
        from .. import random as _random
        is_train = autograd.is_training()
        arg_vals = {k: v._data for k, v in arg_arrays.items()}
        aux_vals = {k: v._data for k, v in aux_arrays.items()}
        key = _random.next_key()
        if _metrics.ENABLED:
            # the gluon analog of the executor's fwd/fwd_bwd accounting:
            # a hybridized step is visible in dispatch_counts() as one
            # xla:fwd plus (when recording) one xla:bwd at backward time
            _metrics.XLA_LAUNCHES.inc(kind="fwd")
        # the env read is short-circuited off the training path and is
        # one dict lookup per inference forward — kept per-call (not a
        # module snapshot) so the knob can be toggled at runtime
        if input_names and not is_train and not autograd.is_recording() \
                and getenv("MXNET_DONATE_INFER", False):
            data_vals = {k: arg_vals[k] for k in input_names
                         if k in arg_vals}
            param_vals = {k: v for k, v in arg_vals.items()
                          if k not in data_vals}
            outs, new_aux = self._get_fwd_donated()(
                data_vals, param_vals, aux_vals, key, is_train)
            out_nds = [NDArray(o, ctx) for o in outs]
            for k, v in new_aux.items():
                aux_arrays[k]._set_data(v)
            return out_nds
        outs, new_aux = self._fwd(arg_vals, aux_vals, key, is_train)
        if _introspect.ENABLED and "fwd" not in self._noted:
            # once per CachedOp: analytical cost of the compiled fwd —
            # the fused-path MFU numerator (a retrace, no XLA compile)
            self._noted.add("fwd")
            _introspect.note_jit("gluon:fwd", self._fwd, arg_vals,
                                 aux_vals, key, is_train)
        out_nds = [NDArray(o, ctx) for o in outs]
        if autograd.is_recording():
            names = list(arg_vals.keys())
            primals = tuple(arg_vals[n] for n in names)
            bwd_jit = self._get_bwd(names)
            aux_snapshot = dict(aux_vals)
            raw_outs = tuple(outs) + tuple(new_aux[k] for k in sorted(new_aux))

            def vjp_fn(cots):
                if _metrics.ENABLED:
                    _metrics.XLA_LAUNCHES.inc(kind="bwd")
                if _introspect.ENABLED and "bwd" not in self._noted:
                    self._noted.add("bwd")
                    _introspect.note_jit("gluon:bwd", bwd_jit, primals,
                                         tuple(cots), aux_snapshot, key,
                                         is_train)
                return bwd_jit(primals, tuple(cots), aux_snapshot, key, is_train)

            autograd._record(None, [arg_arrays[n] for n in names], out_nds,
                             vjp_fn, raw_outs)
        for k, v in new_aux.items():
            aux_arrays[k]._set_data(v)
        return out_nds


class HybridBlock(Block):
    """Parity: gluon/block.py:321."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._reg_params: Dict[str, Parameter] = {}
        self._cached_graph = ()
        self._cached_op = None
        self._active = False
        self._flags = {}

    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._reg_params[name] = value
        if isinstance(value, HybridBlock):
            self._clear_cached_op()
        super().__setattr__(name, value)

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but "
                f"{str(block)} has type {str(type(block))}.")
        super().register_child(block)
        self._clear_cached_op()

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def _clear_cached_op(self):
        self._cached_graph = ()
        self._cached_op = None
        self._cached_by_fmt = {}

    @staticmethod
    def _fmt_key(fmt):
        """Hashable key for an input-structure format (call arity: an RNN
        layer called with vs without explicit states must not share a
        cached graph)."""
        return repr(fmt)

    def _get_graph(self, *args):
        flat_args, in_format = _flatten(args)
        key = self._fmt_key(in_format)
        if not hasattr(self, "_cached_by_fmt"):
            self._cached_by_fmt = {}
        entry = self._cached_by_fmt.get(key)
        if entry is None and getattr(self, "_graph_preset", False) \
                and self._cached_graph:
            # graph preset externally (SymbolBlock imports a ready-made
            # symbol) — adopt it for this call structure
            flat_out = self._cached_graph[1]
            entry = {"graph": self._cached_graph,
                     "out_format": getattr(self, "_out_format", None)
                     or [len(flat_out.list_outputs())]}
            self._cached_by_fmt[key] = entry
        if entry is None or not entry.get("graph"):
            inputs = [sym_mod.Variable(f"data{i}") if len(flat_args) > 1
                      else sym_mod.Variable("data")
                      for i in range(len(flat_args))]
            grouped, _ = _regroup(inputs, in_format)
            params = {name: p.var() for name, p in self._reg_params.items()}
            with self.name_scope():
                out = self.hybrid_forward(sym_mod, grouped, **params) \
                    if not isinstance(grouped, list) else \
                    self.hybrid_forward(sym_mod, *grouped, **params)
            flat_out, out_format = _flatten(out, "output")
            entry = {"graph": (inputs, sym_mod.Group(flat_out)),
                     "out_format": out_format}
            self._cached_by_fmt[key] = entry
        self._in_format = in_format
        self._out_format = entry["out_format"]
        self._cached_graph = entry["graph"]
        return self._cached_graph

    def infer_shape(self, *args):
        self._infer_attrs("shape", *args)

    def _infer_attrs(self, attr, *args):
        inputs, out = self._get_graph(*args)
        flat_args, _ = _flatten(args)
        shapes = {i.name: a.shape for i, a in zip(inputs, flat_args)}
        plan, info, _ = infer_shapes_types(out, shapes, {}, partial=False)
        all_params = {p.name: p for p in self._all_params()}
        for name, struct in info.items():
            if name in all_params and struct is not None:
                all_params[name].shape = tuple(struct.shape)

    def _all_params(self):
        out = list(self.collect_params().values())
        return out

    def _build_cache(self, *args):
        inputs, out = self._get_graph(*args)
        self._cached_op = CachedOp(out)
        # map graph input names → (is_param, source)
        params = {p.name: p for p in self._all_params()}
        self._cached_input_names = [i.name for i in inputs]
        self._cached_params = {
            n: params[n] for n in out.list_inputs() if n in params}
        self._cached_aux = set(out.list_auxiliary_states())
        entry = self._cached_by_fmt[self._fmt_key(self._in_format)]
        entry["op"] = (self._cached_op, self._cached_input_names,
                       self._cached_params, self._cached_aux)

    def _call_cached_op(self, *args):
        flat_args, in_format = _flatten(args)
        entry = getattr(self, "_cached_by_fmt", {}).get(
            self._fmt_key(in_format))
        if entry is not None and "op" in entry:
            # the cached-op analog of the executor's _jit_cache
            # accounting: a hybridized forward that reuses its compiled
            # op is a hit, a (re)trace is a miss — snapshot()["jit_cache"]
            # now covers the gluon path too
            if _metrics.ENABLED:
                _metrics.JIT_CACHE_HITS.inc()
            (self._cached_op, self._cached_input_names,
             self._cached_params, self._cached_aux) = entry["op"]
            self._in_format = in_format
            self._out_format = entry["out_format"]
        else:
            if _metrics.ENABLED:
                _metrics.JIT_CACHE_MISSES.inc()
            self._build_cache(*args)
        arg_dict = {}
        aux_dict = {}
        for name, arr in zip(self._cached_input_names, flat_args):
            arg_dict[name] = arr
        for name, p in self._cached_params.items():
            if name in self._cached_aux:
                aux_dict[name] = p.data()
            else:
                arg_dict[name] = p.data()
        ctx = flat_args[0].context if flat_args else cpu()
        out = self._cached_op(arg_dict, aux_dict, ctx,
                              input_names=self._cached_input_names)
        ret, _ = _regroup(out, self._out_format)
        return ret

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            if self._active:
                try:
                    return self._call_cached_op(x, *args)
                except DeferredInitializationError:
                    self._deferred_infer_shape(x, *args)
                    for p in self.collect_params().values():
                        p._finish_deferred_init()
                    return self._call_cached_op(x, *args)
            try:
                params = {name: p.data() for name, p in self._reg_params.items()}
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for _, p in self._reg_params.items():
                    p._finish_deferred_init()
                params = {name: p.data() for name, p in self._reg_params.items()}
            return self.hybrid_forward(nd, x, *args, **params)
        assert isinstance(x, Symbol), \
            f"HybridBlock requires the first argument to forward be either " \
            f"Symbol or NDArray, but got {type(x)}"
        params = {name: p.var() for name, p in self._reg_params.items()}
        with self.name_scope():
            return self.hybrid_forward(sym_mod, x, *args, **params)

    def _deferred_infer_shape(self, *args):
        try:
            self.infer_shape(*args)
        except Exception as e:
            raise ValueError(
                f"Deferred initialization failed because shape cannot be "
                f"inferred: {e}")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Wrap a Symbol as a Block (parity: gluon/block.py:542)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        self._prefix = ""
        self._params = ParameterDict("", params)
        if isinstance(inputs, Symbol) and len(inputs) == 1:
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(outputs)
        syms = inputs
        input_names = {i.name for i in syms}
        for name in outputs.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            if name not in input_names:
                self.params.get(name, grad_req="null",
                                allow_deferred_init=True)
        self._cached_graph = (syms, outputs)
        self._graph_preset = True  # imported symbol, not traced
        self._reg_params = {}

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            try:
                return self._call_cached_op(x, *args)
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for p in self.collect_params().values():
                    p._finish_deferred_init()
                return self._call_cached_op(x, *args)
        assert isinstance(x, Symbol)
        ret = copy.copy(self._cached_graph[1])
        ret._compose(**{self._cached_graph[0][0].name: x})
        return ret

    def _build_cache(self, *args):
        inputs, out = self._cached_graph
        flat_args, self._in_format = _flatten(args)
        self._out_format = int(0) if len(out) == 1 else [int(0)] * len(out)
        self._cached_op = CachedOp(out)
        params = {p.name: p for p in self.params.values()}
        self._cached_input_names = [i.name for i in inputs]
        self._cached_params = {
            n: params[n] for n in out.list_inputs() if n in params}
        self._cached_aux = set(out.list_auxiliary_states())
        # register in the arity-keyed cache so _call_cached_op reuses the
        # compiled op instead of re-tracing every forward
        if not hasattr(self, "_cached_by_fmt"):
            self._cached_by_fmt = {}
        self._cached_by_fmt[self._fmt_key(self._in_format)] = {
            "graph": self._cached_graph, "out_format": self._out_format,
            "op": (self._cached_op, self._cached_input_names,
                   self._cached_params, self._cached_aux)}

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
