"""Runtime kernel compilation (parity: python/mxnet/rtc.py / include/mxnet/rtc.h).

The reference's CudaModule compiled CUDA C via NVRTC at runtime.  The TPU
analog is runtime Pallas/JAX compilation: `PallasModule` takes python source
defining a kernel function and jit-compiles it for TPU.  The CudaModule name
is retained: it accepts python/pallas source (CUDA C is rejected with a
pointer to the Pallas guide).
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray


class Kernel:
    def __init__(self, fn, name):
        self._fn = fn
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Launch: grid/block dims are ignored (XLA/Mosaic schedules)."""
        vals = [a._data if isinstance(a, NDArray) else a for a in args]
        out = self._fn(*vals)
        return NDArray(out) if not isinstance(out, (list, tuple)) else \
            [NDArray(o) for o in out]


class PallasModule:
    """Compile python source defining jax/pallas kernels at runtime."""

    def __init__(self, source, options=(), exports=()):
        if "__global__" in source or "#include" in source:
            raise MXNetError(
                "CUDA C source is not supported on TPU; write the kernel in "
                "JAX/Pallas (see /opt/skills/guides/pallas_guide.md)")
        import jax
        namespace = {}
        exec(compile(source, "<rtc>", "exec"), namespace)
        self._namespace = namespace
        self.exports = list(exports) or [k for k, v in namespace.items()
                                         if callable(v) and not
                                         k.startswith("_")]

    def get_kernel(self, name, signature=None):
        import jax
        if name not in self._namespace:
            raise MXNetError(f"kernel {name} not found in module; have "
                             f"{self.exports}")
        return Kernel(jax.jit(self._namespace[name]), name)


CudaModule = PallasModule
CudaKernel = Kernel  # reference rtc.CudaKernel role: a launchable handle
