"""Torch function bridge (parity: python/mxnet/torch.py + plugin/torch —
the reference exposed Torch7 tensor math on NDArrays).

Modernized: wraps `torch` (CPU build) callables so they consume/produce
`mxnet_tpu.NDArray` via zero-copy-ish numpy interchange.  Device math
belongs in the native op set; this bridge is the escape hatch for running
torch-only routines inside an mxnet_tpu program, mirroring how the torch
plugin let MXNet users borrow Torch ops.
"""
from __future__ import annotations

import functools
from typing import Any

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, array


def _to_torch(x):
    import torch as _t
    if isinstance(x, NDArray):
        return _t.from_numpy(_np.ascontiguousarray(x.asnumpy()))
    if isinstance(x, (list, tuple)):
        return type(x)(_to_torch(v) for v in x)
    return x


def _from_torch(x, ctx=None):
    import torch as _t
    if isinstance(x, _t.Tensor):
        return array(x.detach().cpu().numpy(), ctx=ctx)
    if isinstance(x, (list, tuple)):
        return type(x)(_from_torch(v, ctx) for v in x)
    return x


def wrap(fn) -> Any:
    """Wrap a torch callable to take/return NDArrays.

        relu = mx.torch.wrap(torch.nn.functional.relu)
        y = relu(mx.nd.array([-1.0, 2.0]))
    """
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        ctx = next((a.context for a in args if isinstance(a, NDArray)), None)
        t_args = [_to_torch(a) for a in args]
        t_kwargs = {k: _to_torch(v) for k, v in kwargs.items()}
        out = fn(*t_args, **t_kwargs)
        return _from_torch(out, ctx)

    return wrapped


def __getattr__(name):
    """mx.torch.<fn> resolves torch.<fn> lazily and wraps it."""
    if name.startswith("__"):  # keep hasattr/introspection contracts intact
        raise AttributeError(name)
    try:
        import torch as _t
    except ImportError as e:  # torch absent: bridge degrades gracefully
        raise AttributeError(f"{name} (torch is not available: {e})") from None
    target = getattr(_t, name, None)
    if target is None or not callable(target):
        raise AttributeError(name)
    return wrap(target)
