"""BucketingModule: one logical model, per-bucket-key executors.

API parity: python/mxnet/module/bucketing_module.py (the reference
shared one memory pool across bucket executors, graph_executor.h:208).
TPU redesign: every bucket is a Module over the SAME symbol family —
parameters live once (shared via `shared_module`), and XLA's per-shape
executable cache plays the role of the reference's pooled workspace, so
switching buckets costs a dict lookup after first compile.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("BucketingModule needs a default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        from ..context import cpu
        # construction kwargs replayed for every per-bucket Module
        self._module_kwargs = dict(
            logger=logger,
            context=context if context is not None else cpu(),
            work_load_list=work_load_list,
            fixed_param_names=fixed_param_names or [],
            state_names=state_names or [],
            group2ctxs=group2ctxs,
            compression_params=compression_params,
        )
        self._reset_bind()
        self._monitor = None
        self._grad_req = None

    # -- internal helpers ---------------------------------------------------
    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._active = None       # the Module handling the current bucket
        self._active_key = None
        self._params_dirty = False

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    @property
    def _default_module(self):
        return self._buckets[self._default_bucket_key]

    def _require(self, params=False, optimizer=False):
        if not self.binded:
            raise MXNetError("BucketingModule not bound")
        if params and not self.params_initialized:
            raise MXNetError("parameters not initialized")
        if optimizer and not self.optimizer_initialized:
            raise MXNetError("optimizer not initialized")

    def _materialize(self, bucket_key, data_shapes, label_shapes,
                     for_training, inputs_need_grad, shared):
        """Build + bind the Module for one bucket key."""
        symbol, data_names, label_names = self._call_sym_gen(bucket_key)
        mod = Module(symbol, data_names, label_names, **self._module_kwargs)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind=False, shared_module=shared,
                 grad_req=self._grad_req)
        return mod

    def _activate(self, bucket_key, data_shapes, label_shapes):
        """switch_bucket body: reuse or materialize, then make current."""
        if bucket_key not in self._buckets:
            mod = self._materialize(
                bucket_key, data_shapes, label_shapes,
                self._active.for_training, self._active.inputs_need_grad,
                shared=self._default_module)
            if self._monitor is not None:
                mod.install_monitor(self._monitor)
            if self.optimizer_initialized:
                mod.borrow_optimizer(self._default_module)
            self._buckets[bucket_key] = mod
        self._active = self._buckets[bucket_key]
        self._active_key = bucket_key

    # -- introspection ------------------------------------------------------
    @property
    def data_names(self):
        if self.binded:
            return self._active.data_names
        return self._call_sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._active.output_names
        return self._call_sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        self._require()
        return self._active.data_shapes

    @property
    def label_shapes(self):
        self._require()
        return self._active.label_shapes

    @property
    def output_shapes(self):
        self._require()
        return self._active.output_shapes

    @property
    def symbol(self):
        self._require()
        return self._active.symbol

    # -- parameters ---------------------------------------------------------
    def get_params(self):
        self._require(params=True)
        self._active._params_dirty = self._params_dirty
        params = self._active.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._require()
        if initializer is None:
            from ..initializer import Uniform
            initializer = Uniform(0.01)
        self._active.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True
        self._params_dirty = False

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            # strict mode routes through init_params (reference behavior)
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            return
        self._active.set_params(arg_params, aux_params,
                                allow_missing=allow_missing,
                                force_init=force_init,
                                allow_extra=allow_extra)
        self.params_initialized = True
        self._params_dirty = False

    # -- binding / bucket switching -----------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            # compare against the DEFAULT bucket's bind state: fit() always
            # re-binds with the default-bucket shapes, and the current
            # bucket may legitimately differ after switch_bucket()
            self._adopt_existing_bind(
                data_shapes, label_shapes, for_training, inputs_need_grad,
                grad_req, against=self._default_module)
            return
        if shared_module is not None:
            raise MXNetError(
                "shared_module is not supported for BucketingModule")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        mod = self._materialize(self._default_bucket_key, data_shapes,
                                label_shapes, for_training,
                                inputs_need_grad, shared=None)
        self._buckets[self._default_bucket_key] = mod
        self._active = mod
        self._active_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        self._require()
        self._activate(bucket_key, data_shapes, label_shapes)

    def prepare(self, data_batch):
        """Pre-materialize the batch's bucket without making it current."""
        self._require(params=True)
        held, held_key = self._active, self._active_key
        self._activate(data_batch.bucket_key, data_batch.provide_data,
                       data_batch.provide_label)
        self._active, self._active_key = held, held_key

    def warmup_buckets(self, buckets, run=True, for_training=False):
        """AOT-warm a set of buckets before traffic (the serving-path
        `warmup()` idea applied to the training/eval bucketing surface):
        `buckets` is an iterable of (bucket_key, data_shapes,
        label_shapes) triples.  Each bucket is materialized (bound,
        params shared) and — with `run=True` — executed once on zeros so
        its XLA programs compile NOW; after warmup, `switch_bucket`
        between warmed keys costs a dict lookup and zero recompiles
        (pinned by tests/test_serving.py).

        `for_training=True` warms the fused forward+backward program
        instead of the inference forward (the two are distinct XLA
        executables — an inference-only warmup leaves the first training
        step on each bucket paying its compile).  The warmup
        forward_backward writes zeros-derived values into the grad
        buffers; they are zeroed afterwards so grad_req='add'
        accumulation never trains on warmup-contaminated gradients."""
        self._require(params=True)
        from .. import ndarray as nd
        from ..io import DataBatch
        if for_training and not self.for_training:
            raise MXNetError(
                "warmup_buckets(for_training=True) on a module bound "
                "with for_training=False")
        held, held_key = self._active, self._active_key
        try:
            for bucket_key, data_shapes, label_shapes in buckets:
                self._activate(bucket_key, data_shapes, label_shapes)
                if not run:
                    continue
                data = [nd.zeros(tuple(d.shape), dtype=getattr(
                    d, "dtype", "float32")) for d in data_shapes]
                label = [nd.zeros(tuple(d.shape), dtype=getattr(
                    d, "dtype", "float32")) for d in (label_shapes or [])]
                batch = DataBatch(data=data, label=label or None, pad=0,
                                  index=None, bucket_key=bucket_key,
                                  provide_data=data_shapes,
                                  provide_label=label_shapes)
                if for_training:
                    ex = self._active._exec
                    # a training-mode forward on the zeros batch also
                    # advances aux state (BatchNorm moving stats) —
                    # snapshot and restore so warmup mutates NOTHING
                    aux_snap = {k: v._data for k, v in ex.aux_dict.items()}
                    self._active.forward_backward(batch)
                    for k, v in aux_snap.items():
                        ex.aux_dict[k]._set_data(v)
                    # scrub the warmup grads: under grad_req='add' they
                    # would otherwise accumulate into the first real step
                    for g in ex.grad_dict.values():
                        if g is not None:
                            g._set_data(nd.zeros(
                                g.shape, dtype=g.dtype)._data)
                else:
                    self._active.forward(batch, is_train=False)
        finally:
            self._active, self._active_key = held, held_key

    # -- generative decode --------------------------------------------------
    def attach_decode_engine(self, engine) -> None:
        """Route this module's generation through a continuous-batching
        ``serving.decode.DecodeEngine`` (per-step join/leave, paged KV,
        EDF shedding).  The engine owns its own decode model/params —
        build one with ``serving.decode.CellModel`` over a steppable
        rnn cell to serve the cell family this module trains."""
        self._decode_engine = engine

    def generate(self, prompt, max_new_tokens, **kw):
        """Generate ``max_new_tokens`` greedy tokens after ``prompt``
        (a token-id sequence) through the attached decode engine.

        Without an attached engine this raises a typed
        ``GenerativeRouteError`` instead of falling back to per-bucket
        ``forward`` loops or the request-coalescing serving tier —
        generation riding either path pins a whole batch for one
        sequence's full output length (the hostage path this method
        closes; regression-pinned in tests/test_decode.py)."""
        from ..serving.decode import GenerativeRouteError
        eng = getattr(self, "_decode_engine", None)
        if eng is None:
            raise GenerativeRouteError(
                "BucketingModule has no decode engine attached — "
                "generation must not ride the bucketed forward path "
                "(one sequence would hold a whole padded batch for "
                "its full output length).  attach_decode_engine("
                "serving.decode.DecodeEngine(...)) first; see "
                "docs/decode_serving.md")
        return eng.generate(prompt, max_new_tokens, **kw)

    # -- compute ------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        self._require(params=True)
        self._activate(data_batch.bucket_key, data_batch.provide_data,
                       data_batch.provide_label)
        self._active.forward(data_batch, is_train=is_train)

    def forward_backward(self, data_batch):
        self._require(params=True)
        self._activate(data_batch.bucket_key, data_batch.provide_data,
                       data_batch.provide_label)
        self._active.forward_backward(data_batch)

    def backward(self, out_grads=None):
        self._require(params=True)
        self._active.backward(out_grads=out_grads)

    def update(self):
        self._require(params=True, optimizer=True)
        self._params_dirty = True
        self._active.update()

    def get_outputs(self, merge_multi_context=True):
        self._require(params=True)
        return self._active.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        self._require(params=True)
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return self._active.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._require(params=True)
        self._active.update_metric(eval_metric, labels)

    # -- optimizer / monitor ------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._require(params=True)
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._active.init_optimizer(kvstore, optimizer, optimizer_params,
                                    force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._active:
                mod.borrow_optimizer(self._active)
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        self._require()
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)
