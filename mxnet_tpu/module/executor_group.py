"""DataParallelExecutorGroup — compatibility shim.

Reference parity: `python/mxnet/module/executor_group.py:128` sliced each
batch across per-device executors.  The TPU design replaces this with ONE
mesh-sharded executor (see `mxnet_tpu.module.module.Module.bind` and
`mxnet_tpu.parallel.data_parallel`): batch sharded on the 'dp' mesh axis,
parameters replicated, XLA inserting the gradient all-reduce.  This class is
kept for API compatibility with code that instantiated the group directly;
it wraps the mesh path.
"""
from __future__ import annotations

from typing import List

import numpy as _np

from ..base import MXNetError


def _split_input_slice(batch_size: int, work_load_list: List[float]):
    """Parity: python/mxnet/executor_manager.py:_split_input_slice."""
    total_work_load = sum(work_load_list)
    batch_num_list = [round(work_load * batch_size / total_work_load)
                      for work_load in work_load_list]
    batch_num_sum = sum(batch_num_list)
    if batch_num_sum < batch_size:
        batch_num_list[-1] += batch_size - batch_num_sum
    slices = []
    end = 0
    for batch_num in batch_num_list:
        begin = int(min(end, batch_size))
        end = int(min(begin + batch_num, batch_size))
        if begin >= end:
            raise MXNetError("Too many slices; some splits are empty")
        slices.append(slice(begin, end))
    return slices


class DataParallelExecutorGroup:
    """Thin wrapper: a Module bound with multiple contexts already IS the
    data-parallel group (one sharded executor). Provided for source parity."""

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        from .module import Module
        data_names = [d[0] if isinstance(d, tuple) else d.name
                      for d in data_shapes]
        label_names = [d[0] if isinstance(d, tuple) else d.name
                       for d in (label_shapes or [])]
        self._module = Module(symbol, data_names, label_names,
                              context=contexts,
                              fixed_param_names=fixed_param_names,
                              state_names=state_names)
        self._module.bind(data_shapes, label_shapes, for_training,
                          inputs_need_grad, grad_req=grad_req)

    def forward(self, data_batch, is_train=None):
        self._module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._module.backward(out_grads)

    def get_outputs(self, merge_multi_context=True):
        return self._module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._module.update_metric(eval_metric, labels)
