"""Module: intermediate-level training API over one compiled executor.

Reference parity: `python/mxnet/module/module.py:39` (bind/init_params/
init_optimizer/forward/backward/update + kvstore wiring, model.py:97-138).

TPU redesign of the multi-device path: where the reference's
DataParallelExecutorGroup (`executor_group.py:128`) sliced each batch across
per-GPU executors and pushed gradients through KVStore reduce, a Module bound
with several contexts builds ONE executor over a `jax.sharding.Mesh` of those
devices — batch sharded on 'dp', parameters replicated, gradient all-reduce
inserted by XLA over ICI.  KVStore('tpu_sync') then applies the optimizer to
the replicated gradients (update_on_kvstore semantics preserved).
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ..base import MXNetError
from ..context import Context, cpu
from ..initializer import InitDesc, Uniform
from .. import ndarray as nd
from ..io import DataDesc
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from ..observability.tracing import trace_span
from .. import optimizer as opt
from ..model import _create_kvstore, load_checkpoint, save_checkpoint
from .base_module import BaseModule, _check_input_names


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=cpu(), work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        self._group2ctxs = group2ctxs
        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) if fixed_param_names \
            is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = "write"

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        reference_format=False):
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, *self.get_params(),
                        reference_format=reference_format)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    # -- shapes ---------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, tuple(o.shape)) for n, o in
                zip(self._output_names, self._exec.outputs)] \
            if self._exec._outputs_cache is not None else \
            list(zip(self._output_names, self._infer_output_shapes()))

    def _infer_output_shapes(self):
        shapes = {d.name: d.shape for d in self._data_shapes}
        if self._label_shapes:
            shapes.update({d.name: d.shape for d in self._label_shapes})
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return out_shapes

    # -- params ---------------------------------------------------------------
    def get_params(self):
        assert self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if self._arg_params is None:
            with _memory.memory_scope("param"):
                self._arg_params = {
                    name: nd.zeros(arr.shape, dtype=arr.dtype)
                    for name, arr in self._exec.arg_dict.items()
                    if name in self._param_names}
        if self._aux_params is None:
            with _memory.memory_scope("param"):
                self._aux_params = {
                    name: nd.zeros(arr.shape, dtype=arr.dtype)
                    for name, arr in self._exec.aux_dict.items()}
        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    cache_arr.copyto(arr)
            elif not allow_missing and cache is not None:
                raise RuntimeError(f"{name} is not presented")
            elif initializer is not None:
                initializer(InitDesc(name, attrs.get(name)), arr)

        for name in self._param_names:
            arr = self._arg_params[name]
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)
        self.params_initialized = True
        self._params_dirty = False
        self._exec.copy_params_from(self._arg_params, self._aux_params,
                                    allow_extra_params=True)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            return
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        self.params_initialized = True
        self._params_dirty = False

    def _sync_params_from_devices(self):
        """Refresh the host-side param mirror by POINTER HANDOFF, not
        copy: jax arrays are immutable (the executor swaps whole buffers
        on update, never mutates), so aliasing is safe — and the per-
        param device_put the old copyto loop paid was O(params) tunnel
        RPCs per epoch (fit() syncs every epoch for the epoch-end
        callback; 2x193 RPCs/epoch on ResNet-50)."""
        fused_active = self.__dict__.get("_fstep") is not None

        def _handoff(src_nd, tgt_nd):
            data = src_nd._data
            if fused_active:
                # the fused train step DONATES param buffers each step;
                # a handed-off alias held by the user (get_params,
                # epoch-end callback) would be invalidated on the next
                # step — give them their own buffer instead
                import jax.numpy as jnp
                data = jnp.array(data)
            if data.dtype != tgt_nd.dtype:
                data = data.astype(tgt_nd.dtype)
            tgt_nd._set_data(data)

        for name in self._param_names:
            _handoff(self._exec.arg_dict[name], self._arg_params[name])
        for name, arr in self._exec.aux_dict.items():
            _handoff(arr, self._aux_params[name])
        self._params_dirty = False

    # -- bind -----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self._adopt_existing_bind(data_shapes, label_shapes,
                                      for_training, inputs_need_grad,
                                      grad_req)
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        assert not for_training or label_shapes is not None or \
            not self._label_names

        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        self._label_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                              for d in label_shapes] if label_shapes else []

        shapes = {d.name: tuple(d.shape) for d in self._data_shapes}
        shapes.update({d.name: tuple(d.shape) for d in self._label_shapes})
        types = {d.name: d.dtype for d in
                 self._data_shapes + self._label_shapes}

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        arg_types, _, aux_types = self._symbol.infer_type(**types)
        arg_names = self._symbol.list_arguments()
        ctx0 = self._context[0]

        mesh = None
        data_shard_args = ()
        if len(self._context) > 1:
            from ..parallel.mesh import make_mesh
            devs = [c.jax_device() for c in self._context]
            mesh = make_mesh(dp=len(devs), devices=devs)
            data_shard_args = tuple(self._data_names) + tuple(self._label_names)

        args, grads, reqs = {}, {}, {}
        shared_args = shared_module._exec.arg_dict if shared_module else {}
        shared_aux = shared_module._exec.aux_dict if shared_module else {}
        # HBM ledger: bind-time buffers are the symbolic path's params/
        # grads — tag them like the gluon owners so Module.fit training
        # attributes the same way a gluon Trainer run does (the inner
        # "grad" scope overrides for gradient buffers; innermost wins)
        with _memory.memory_scope("param"):
            for name, shp, dt in zip(arg_names, arg_shapes, arg_types):
                if name in shared_args and \
                        tuple(shared_args[name].shape) == tuple(shp):
                    args[name] = shared_args[name]
                else:
                    args[name] = nd.zeros(shp, ctx=ctx0, dtype=dt)
                is_input = name in self._data_names \
                    or name in self._label_names \
                    or name in self._state_names
                if not for_training:
                    reqs[name] = "null"
                elif is_input:
                    if name in self._data_names and inputs_need_grad:
                        reqs[name] = "write"
                    else:
                        reqs[name] = "null"
                elif name in self._fixed_param_names:
                    reqs[name] = "null"
                else:
                    reqs[name] = grad_req if isinstance(grad_req, str) else \
                        grad_req.get(name, "write")
                if reqs[name] != "null":
                    with _memory.memory_scope("grad"):
                        grads[name] = nd.zeros(shp, ctx=ctx0, dtype=dt)
            aux = {}
            for name, shp, dt in zip(self._aux_names, aux_shapes, aux_types):
                if name in shared_aux and \
                        tuple(shared_aux[name].shape) == tuple(shp):
                    aux[name] = shared_aux[name]
                else:
                    aux[name] = nd.zeros(shp, ctx=ctx0, dtype=dt)

        if mesh is not None:
            # keep params/grads/aux replicated over the mesh so optimizer
            # updates and kvstore pulls stay SPMD-consistent
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(mesh, P())
            from ..ndarray.sparse import BaseSparseNDArray
            for d in (args, grads, aux):
                for k, v in d.items():
                    if k not in data_shard_args and \
                            not isinstance(v, BaseSparseNDArray):
                        v._set_data(jax.device_put(v._data, repl))

        from ..executor import Executor
        group2ctx = None
        if self._group2ctxs:
            group2ctx = self._group2ctxs if isinstance(self._group2ctxs, dict) \
                else self._group2ctxs[0]
        self._exec = Executor(self._symbol, ctx0, args, grads, reqs, aux,
                              group2ctx=group2ctx,
                              shared_exec=shared_module._exec if shared_module
                              else None,
                              mesh=mesh, data_shard_args=data_shard_args)
        # Embedding(sparse_grad=True) weights get ROW-SPARSE grad buffers
        # (parity: infer-storage marking the weight grad rsp,
        # indexing_op.h) — the EXECUTOR owns eligibility (it disables the
        # rewrite under remat/group2ctx), so the storage swap follows its
        # decision rather than duplicating the predicate here
        from ..ndarray.sparse import zeros_sparse
        for name in self._exec._rsp_grad_args:
            tgt = self._exec.grad_dict.get(name)
            if tgt is not None:
                self._exec.grad_dict[name] = zeros_sparse(
                    "row_sparse", tgt.shape, ctx=ctx0, dtype=tgt.dtype)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    # -- optimizer ------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            # pre-initialized optimizer state is adopted silently — the
            # pre-bind + pre-init + fit() pattern is first-class (bench,
            # resume-from-checkpoint); force_init=True replaces it
            self.logger.debug("optimizer already initialized, adopting")
            return
        if self._params_dirty:
            self._sync_params_from_devices()
        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), dict(zip(self._param_names,
                                                  [self._exec.arg_dict[n]
                                                   for n in self._param_names])))
        batch_size = self._data_shapes[0].shape[0]
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {i: n for i, n in enumerate(self._param_names)}
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but rescale_grad "
                    f"is not normalized to 1.0/batch_size/num_workers ({rescale_grad} "
                    f"vs. {optimizer.rescale_grad}). Is this intended?")
            if not optimizer.idx2name:
                optimizer.idx2name = idx2name.copy()

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, name in enumerate(self._param_names):
                # init from the executor's (possibly mesh-replicated) buffers
                # so kvstore-side updates stay SPMD-consistent
                kvstore.init(name, self._exec.arg_dict[name])
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- compute --------------------------------------------------------------
    @staticmethod
    def _load_arg(arr, tgt):
        """Batch data typed AND placed like the executor's buffer (the
        reference copies batches to executor contexts in _load_data,
        executor_group.py:28-71 — a CPU-built mx.nd.array fed to a
        TPU-bound module must hop devices here, and a mesh-sharded
        target keeps its sharding so re-jit never triggers).  The
        dtype-cast + sharding-preserving placement rule lives in ONE
        place: NDArray.copyto."""
        if isinstance(arr, nd.NDArray):
            arr.copyto(tgt)
        else:
            # host (numpy) batch: one transfer, straight to the
            # executor's placement — no default-device stopover
            import jax
            import numpy as _np
            want = getattr(tgt._data, "sharding", None) \
                or tgt.context.jax_device()
            val = _np.asarray(arr, dtype=tgt.dtype)
            if _metrics.ENABLED:
                _metrics.DEVICE_PUTS.inc()
                _metrics.TRANSFER_BYTES.inc(val.nbytes)
            tgt._set_data(jax.device_put(val, want))

    def _set_batch(self, data_batch, is_train):
        for name, arr in zip(self._data_names, data_batch.data):
            tgt = self._exec.arg_dict[name]
            if tuple(tgt.shape) != tuple(arr.shape):
                # shape change (e.g. last partial batch): XLA re-specializes;
                # placement decided by the buffer, same rule as copyto
                src = arr if isinstance(arr, nd.NDArray) \
                    else nd.array(arr, ctx=tgt.context)
                self._exec.arg_dict[name] = \
                    src.astype(tgt.dtype).copyto(tgt.context)
            else:
                self._load_arg(arr, tgt)
        if is_train and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                if name not in self._exec.arg_dict:
                    continue
                self._load_arg(arr, self._exec.arg_dict[name])

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._set_batch(data_batch, is_train or bool(data_batch.label))
        self._exec.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Fused single-compiled-call training step (TPU hot path)."""
        assert self.binded and self.params_initialized
        # a stale flag from a fused step whose update() was skipped must
        # not swallow the NEXT standard-path update
        self.__dict__.pop("_fused_stepped", None)
        if self._maybe_fused_train_step(data_batch):
            return
        self._set_batch(data_batch, True)
        self._exec.forward_backward()

    # -- single-program train step (MXNET_FUSED_STEP=1) ---------------------
    def _fused_step_updater(self):
        if self._update_on_kvstore and self._kvstore is not None:
            return getattr(self._kvstore, "_updater", None)
        return self._updater

    def _fused_step_eligible(self):
        """ONE donated XLA program per step (fwd+bwd+optimizer) — the
        full engine-bulking limit.  Opt-in (MXNET_FUSED_STEP=1) because
        it changes two observable contracts: grad_dict is not
        materialized per step, and params/optimizer states are donated
        (updated in place device-side)."""
        from ..base import getenv
        from ..optimizer import FusedUpdater
        if not getenv("MXNET_FUSED_STEP", 0):
            return False
        if not self.optimizer_initialized:
            return False
        ex = self._exec
        upd = self._fused_step_updater()
        ok = (isinstance(upd, FusedUpdater)
              and getattr(upd.optimizer, "fused", False)
              and ex._mesh is None and not ex.group2ctx
              and not ex._rsp_grad_args
              and ex._monitor is None
              and not ex._remat  # mirror remat rides the standard path
              and not self.inputs_need_grad
              and not getattr(self._kvstore, "_gc", None)
              and (self._kvstore is None
                   or self._kvstore.num_workers == 1)
              and all(ex.grad_req.get(n, "null") in ("null", "write")
                      for n in ex.arg_dict))
        if not ok and not self.__dict__.get("_fstep_warned"):
            self.logger.warning(
                "MXNET_FUSED_STEP=1 requested but this module is not "
                "eligible (needs: fused optimizer, single device, dense "
                "write grads, no compression/monitor) — using the "
                "standard 2-program step")
            self._fstep_warned = True
        return ok

    def _maybe_fused_train_step(self, data_batch):
        if not self._fused_step_eligible():
            return False
        import jax
        import jax.numpy as jnp
        import numpy as _np
        from .. import random as _random

        ex = self._exec
        upd = self._fused_step_updater()
        opt_ = upd.optimizer
        self._set_batch(data_batch, True)
        arg_vals = {k: v._data for k, v in ex.arg_dict.items()}
        aux_vals = {k: v._data for k, v in ex.aux_dict.items()}
        feed = set(self._data_names) | set(self._label_names)
        grad_names = [n for n in ex._grad_names if n not in feed]
        pnames = [n for n in arg_vals if n not in feed]

        live = [(i, n) for i, n in enumerate(self._param_names)
                if n in ex.grad_dict]
        idx_of = {n: i for i, n in live}
        kv_key = bool(self._update_on_kvstore and self._kvstore is not None)
        from ..kvstore import _updater_key as _ukey
        for i, n in live:
            upd._ensure_state(_ukey(n) if kv_key else i,
                              ex.arg_dict[n])
            opt_._update_count(_ukey(n) if kv_key else i)
        ukeys = {n: (_ukey(n) if kv_key else idx_of[n]) for _, n in live}

        fs = self.__dict__.get("_fstep")
        fkey = (id(ex._plan), type(opt_).__name__,
                opt_.fused_hyper_key(), tuple(sorted(grad_names)),
                tuple(pnames))
        if fs is None or fs["key"] != fkey:
            plan = ex._plan
            gset = list(grad_names)

            def ftrain(params, states, aux, xs, key, lrs, wds, ts):
                merged = dict(params)
                merged.update(xs)

                def fwd(p):
                    m = dict(merged)
                    m.update(p)
                    return plan.run(m, aux, key, True)

                (outs, new_aux), vjp = jax.vjp(
                    fwd, {n: params[n] for n in gset})
                cots = ([jnp.ones(o.shape, o.dtype) for o in outs],
                        jax.tree_util.tree_map(jnp.zeros_like, new_aux))
                (grads,) = vjp(cots)
                new_p, new_s = dict(params), dict(states)
                for k, n in enumerate(sorted(gset)):
                    nw, ns = opt_._fused_step_mp(
                        ukeys[n], params[n], grads[n], states[n],
                        lrs[k], wds[k], ts[k])
                    new_p[n] = (nw if nw.dtype == params[n].dtype
                                else nw.astype(params[n].dtype))
                    new_s[n] = jax.tree_util.tree_map(
                        lambda a, b: a if a.dtype == b.dtype
                        else a.astype(b.dtype), ns, states[n])
                return outs, new_aux, new_p, new_s, ts + 1

            # hold the plan ref: id() keys must not be recycled
            fs = {"key": fkey, "plan": plan,
                  "fn": jax.jit(ftrain, donate_argnums=(0, 1, 2))}
            self._fstep = fs

        snames = sorted(grad_names)
        # hyper/ts device caches shared with FusedUpdater.update_all
        lrs, wds, ts, commit_ts = upd.hyper_arrays(
            tuple(ukeys[n] for n in snames))

        params = {n: arg_vals[n] for n in pnames}
        states = {n: upd._state_data(upd.states[ukeys[n]])
                  for n in snames}
        xs = {n: arg_vals[n] for n in feed if n in arg_vals}
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="fused_step")
            _metrics.OPTIMIZER_STEPS.inc()
        with trace_span("fused_train_step", cat="executor"):
            outs, new_aux, new_p, new_s, nts = fs["fn"](
                params, states, aux_vals, xs, _random.next_key(),
                lrs, wds, ts)
        commit_ts(nts)

        kv_store = (self._kvstore._store
                    if kv_key and hasattr(self._kvstore, "_store")
                    else None)
        for n in pnames:
            ex.arg_dict[n]._set_data(new_p[n])
            if kv_store is not None and n in kv_store:
                # keep the kvstore's weight copy current: a later
                # pushpull/pull (eligibility flips mid-run) must not
                # revert training to stale buffers
                kv_store[n]._set_data(new_p[n])
        for n in snames:
            upd.states[ukeys[n]] = upd._state_writeback(
                upd.states[ukeys[n]], new_s[n])
        ex._set_results(outs, new_aux)
        ex._snapshot = None
        ex._pending_grads = None
        self._params_dirty = True
        self._fused_stepped = True
        return True

    def update(self):
        """Parity: _update_params_on_kvstore / _update_params (model.py:97-138).

        TPU hot path: the whole multi-parameter update runs in O(1) XLA
        dispatches — KVStore.pushpull / FusedUpdater.update_all trace every
        key into one compiled program (the engine-bulking analog,
        graph_executor.cc:1350) instead of the reference's per-key engine
        pushes."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        if self.__dict__.pop("_fused_stepped", False):
            return  # the fused train step already applied the update
        self._params_dirty = True
        live = [(i, n) for i, n in enumerate(self._param_names)
                if n in self._exec.grad_dict]
        names = [n for _, n in live]
        grads = [self._exec.grad_dict[n] for n in names]
        if self._kvstore is not None:
            if self._update_on_kvstore:
                self._kvstore.pushpull(
                    names, [[g] for g in grads],
                    out=[[self._exec.arg_dict[n]] for n in names])
            else:
                aggs = [nd.zeros(g.shape, dtype=g.dtype) for g in grads]
                self._kvstore.pushpull(names, [[g] for g in grads],
                                       out=[[a] for a in aggs])
                self._update_local([i for i, _ in live], aggs, names)
        else:
            self._update_local([i for i, _ in live], grads, names)

    def _update_local(self, indices, grads, names):
        from ..optimizer import FusedUpdater
        weights = [self._exec.arg_dict[n] for n in names]
        if isinstance(self._updater, FusedUpdater):
            self._updater.update_all(indices, grads, weights)
        else:
            for i, g, w in zip(indices, grads, weights):
                self._updater(i, g, w)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels or [])),
            dict(zip(self._output_names, self.get_outputs())))

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    def get_optimizer_states_bytes(self) -> bytes:
        """Optimizer state as one bytes payload — the Module's durable
        checkpoint surface (mxnet_tpu.checkpoint / fit(checkpoint_dir))."""
        assert self.optimizer_initialized
        updater = self._kvstore._updater if self._update_on_kvstore \
            else self._updater
        if updater is None:
            raise MXNetError("no optimizer set")
        return updater.get_states()

    def set_optimizer_states_bytes(self, payload: bytes) -> None:
        assert self.optimizer_initialized
        updater = self._kvstore._updater if self._update_on_kvstore \
            else self._updater
        if updater is None:
            raise MXNetError("no optimizer set")
        updater.set_states(payload)

    def save_optimizer_states(self, fname):
        from ..base import atomic_write
        atomic_write(fname, self.get_optimizer_states_bytes())

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self.set_optimizer_states_bytes(f.read())

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        self._label_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                              for d in label_shapes] if label_shapes else []
        shapes = {d.name: tuple(d.shape) for d in
                  self._data_shapes + self._label_shapes}
        self._exec = self._exec.reshape(**shapes)

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True
