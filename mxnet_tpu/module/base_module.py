"""BaseModule: the high-level train/predict interface.

Reference parity: `python/mxnet/module/base_module.py` — fit (:376-487),
score, predict, forward/backward contract, parameter get/set, checkpoints.
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

import numpy as _np

from ..base import MXNetError
from .. import metric as _metric
from .. import ndarray as nd
from ..io import DataDesc
from ..model import BatchEndParam
from ..observability import flight as _flight
from ..observability import metrics as _obs
from ..observability.tracing import step_span, trace_span


def _check_input_names(symbol, names, typename, throw):
    args = symbol.list_arguments()
    for name in names:
        if name not in args:
            msg = f"You created Module with Module(..., {typename}_names={names})" \
                  f" but input with name '{name}' is not found in symbol.list_arguments()."
            if throw:
                raise ValueError(msg)
            logging.warning(msg)


def _as_list(obj):
    if obj is None:
        return []
    return obj if isinstance(obj, (list, tuple)) else [obj]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level ----------------------------------------------------------
    def forward_backward(self, data_batch) -> None:
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Parity: base_module.score."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Parity: base_module.predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise ValueError("Cannot merge batches: different number of outputs")
            output_list2 = [nd.concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, checkpoint_dir=None, checkpoint_period=1,
            checkpoint_max_keep=None, supervise=False):
        """Train loop (parity: base_module.py:376-487).

        ``checkpoint_dir`` opts into the fault-tolerant checkpoint
        subsystem (docs/checkpointing.md): fit auto-resumes from the
        newest valid checkpoint there (params + optimizer state;
        ``begin_epoch`` advances to the saved epoch), saves one atomic
        async checkpoint every ``checkpoint_period`` epochs, keeps the
        newest ``checkpoint_max_keep`` (None = all), and barriers on
        outstanding writes before returning.

        ``supervise=True`` runs every fit step through a
        ``gluon.TrainingSupervisor`` (docs/training_resilience.md):
        transient step failures restore a rolling host snapshot of
        params + optimizer state and replay; divergence and stall
        watchdogs post-mortem and raise typed errors.  Inert under
        ``MXNET_SUPERVISE=0``."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        if initializer is None:
            initializer = Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        _ckpt = None
        if checkpoint_dir is not None:
            from .. import checkpoint as _ckpt_mod
            _ckpt = _ckpt_mod.CheckpointManager(
                checkpoint_dir, max_to_keep=checkpoint_max_keep)
            restored = _ckpt.restore()
            if restored is not None:
                ck_epoch, ck_state = restored
                ck_arg, ck_aux, ck_opt, _ = \
                    _ckpt_mod.unpack_module_state(ck_state)
                self.set_params(
                    {k: nd.array(v) for k, v in ck_arg.items()},
                    {k: nd.array(v) for k, v in ck_aux.items()})
                if ck_opt is not None:
                    if hasattr(self, "set_optimizer_states_bytes"):
                        self.set_optimizer_states_bytes(ck_opt)
                    else:
                        # BucketingModule/SequentialModule never had a
                        # durable optimizer-state surface (no
                        # save_optimizer_states either): params resume,
                        # optimizer restarts fresh — say so
                        self.logger.warning(
                            "checkpoint carries optimizer state but %s "
                            "cannot restore it; resuming params only",
                            type(self).__name__)
                begin_epoch = max(begin_epoch, int(ck_epoch))
                self.logger.info(
                    "fit: resumed from checkpoint epoch %d in %s",
                    ck_epoch, checkpoint_dir)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        _sup = None
        if supervise:
            from ..gluon.supervisor import TrainingSupervisor
            _sup = TrainingSupervisor.for_module(self)

        global_step = 0
        try:
            self._fit_epochs(
                train_data, eval_data, eval_metric, validation_metric,
                epoch_end_callback, batch_end_callback, eval_end_callback,
                eval_batch_end_callback, monitor, begin_epoch, num_epoch,
                global_step, _ckpt, checkpoint_period, _sup)
        finally:
            if _sup is not None:
                _sup.close()
            if _ckpt is not None:
                _ckpt.close()  # barrier: all queued writes committed

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, epoch_end_callback,
                    batch_end_callback, eval_end_callback,
                    eval_batch_end_callback, monitor, begin_epoch,
                    num_epoch, global_step, _ckpt, checkpoint_period,
                    _sup=None):
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            # decided ONCE per epoch: flipping the recorder on mid-epoch
            # must not fabricate a span with a t0 from before the flip
            ep_t0 = _flight.now_us() if _flight.ENABLED else None
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            with trace_span("data_fetch", cat="io"), \
                    _flight.phase_span("data_wait", cat="io"):
                next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                # dispatch accounting: the per-step delta of compiled
                # launches + device_puts over forward_backward+update is
                # the round-2 O(1) invariant, published as a gauge.
                # kind="data" launches are excluded: a PrefetchingIter
                # producer thread issues them DURING the step, which
                # would make the delta nondeterministic.
                obs_on = _obs.ENABLED
                if obs_on:
                    d0 = _obs.step_dispatches()
                with step_span(global_step):
                    if _sup is not None:
                        # supervised: fwd/bwd/update run as ONE step_fn
                        # under retry + divergence/stall watchdogs
                        _sup.step(data_batch)
                    else:
                        self.forward_backward(data_batch)
                        with trace_span("update", cat="optimizer"):
                            self.update()
                if obs_on:
                    _obs.FIT_STEP_DISPATCHES.set(_obs.step_dispatches() - d0)
                global_step += 1
                try:
                    # iterators that time their own consumer-side stall
                    # (PrefetchingIter) must not be counted again here
                    if obs_on and not getattr(
                            data_iter, "_self_timed_data_wait", False):
                        t0 = time.perf_counter()
                        with trace_span("data_fetch", cat="io"), \
                                _flight.phase_span("data_wait", cat="io",
                                                   step=global_step):
                            next_data_batch = next(data_iter)
                        _obs.DATA_WAIT_SECONDS.observe(
                            time.perf_counter() - t0)
                    else:
                        with trace_span("data_fetch", cat="io"), \
                                _flight.phase_span("data_wait", cat="io",
                                                   step=global_step):
                            next_data_batch = next(data_iter)
                    self.prepare(next_data_batch)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    batch_end_params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                                     eval_metric=eval_metric,
                                                     locals=locals())
                    for callback in _as_list(batch_end_callback):
                        callback(batch_end_params)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_params_, aux_params_)
            if _ckpt is not None and (
                    (epoch + 1) % max(1, checkpoint_period) == 0
                    or epoch == num_epoch - 1):  # final epoch always saved
                from .. import checkpoint as _ckpt_mod
                # async: the device->host snapshot happens here, the
                # serialize+write happens off the epoch loop.  Module
                # types without an optimizer-state surface checkpoint
                # params only (same coverage the legacy path had).
                opt_bytes = self.get_optimizer_states_bytes() \
                    if hasattr(self, "get_optimizer_states_bytes") else None
                _ckpt.save(epoch + 1, _ckpt_mod.pack_module_state(
                    self.symbol, arg_params_, aux_params_,
                    optimizer_states=opt_bytes))

            if eval_data:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()
            if ep_t0 is not None:
                # non-lexical span (the epoch body is one loop pass):
                # recorded via the raw clock + record() pair
                _flight.record("fit_epoch", "train", ep_t0,
                               _flight.now_us(), step=epoch)

    def _adopt_existing_bind(self, data_shapes, label_shapes, for_training,
                             inputs_need_grad=False, grad_req="write",
                             against=None):
        """Already-bound handshake shared by every Module subclass: a
        re-bind matching the current bind (data/label name+shape+dtype,
        training mode, inputs_need_grad, grad_req) is a silent no-op; a
        conflict raises instead of warn-and-ignore, which would silently
        keep stale executors.  `against` overrides the module whose bind
        state is compared (BucketingModule compares the default bucket,
        not whichever bucket is current)."""
        from ..io import DataDesc
        import numpy as _np
        ref = against if against is not None else self

        def norm(descs):
            out = []
            for d in (descs or []):
                d = d if isinstance(d, DataDesc) else DataDesc(*d)
                out.append((d.name, tuple(d.shape),
                            _np.dtype(d.dtype).name))
            return out

        req = (norm(data_shapes), norm(label_shapes), bool(for_training),
               bool(inputs_need_grad), grad_req)
        cur = (norm(ref.data_shapes), norm(ref.label_shapes),
               bool(ref.for_training), bool(ref.inputs_need_grad),
               getattr(ref, "_grad_req", grad_req))
        if req != cur:
            raise ValueError(
                "Module is already bound with (data, label, for_training, "
                "inputs_need_grad, grad_req)=%s; bind(%s) conflicts. "
                "Use force_rebind=True." % (cur, req))

    # -- interface to implement ----------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params, aux_params = {}, {}
        for k, value in save_dict.items():
            arg_type, _, name = k.partition(":")
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError(f"Invalid param file {fname}")
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized

    def install_monitor(self, mon):
        raise NotImplementedError

    def prepare(self, data_batch):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError
