"""Sparse NDArrays: row_sparse + csr (parity: python/mxnet/ndarray/sparse.py,
include/mxnet/ndarray.h:61-63, src/operator/tensor/cast_storage / dot sparse).

Storage behavior, not just storage API (VERDICT r2 #4): a
RowSparseNDArray holds ONLY `indices` (sorted unique row ids) and
`values` (the stored rows) — the O(vocab) dense form is never
materialized at construction.  Dense materialization happens lazily and
only at explicit dense sinks (`tostype('default')`, `asnumpy`, mixing
into dense arithmetic), mirroring the reference where rsp tensors flow
rows-only through optimizer/kvstore hot paths
(src/operator/optimizer_op.cc:39-287 rsp kernels,
src/kvstore/kvstore_local.h rsp paths) and only CastStorageComputeEx
produces a dense array.

XLA has no first-class sparsity (SURVEY.md §7), so *inside compiled
graphs* compute stays dense; the rows-only representation lives at the
NDArray/eager layer where the memory wins matter (embedding gradients:
nnz = tokens-per-batch vs vocab).
"""
from __future__ import annotations

import functools as _functools
import os

import jax as _jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import current_context
from .ndarray import NDArray, array, zeros


def _dedup_rows(indices, values):
    """Sorted-unique row ids + segment-summed values (eager, O(nnz));
    establishes the reference rsp invariant (sorted, no duplicates)."""
    indices = jnp.asarray(indices, jnp.int64).reshape(-1)
    values = jnp.asarray(values)
    uids, inv = jnp.unique(indices, return_inverse=True)
    if uids.shape[0] == indices.shape[0]:
        # already unique; unique() returns them sorted — reorder values
        order = jnp.argsort(indices)
        return indices[order], values[order]
    summed = jnp.zeros((uids.shape[0],) + values.shape[1:],
                       values.dtype).at[inv.reshape(-1)].add(values)
    return uids, summed


class _RspCot:
    """Autograd cotangent marker for a row-sparse gradient: (row ids,
    row values) that MUST NOT be densified while flowing through the
    tape.  Duplicated ids are allowed here (dedup happens once at
    deposit time / construction of the RowSparseNDArray)."""

    __slots__ = ("ids", "vals", "shape")

    def __init__(self, ids, vals, shape):
        self.ids = jnp.asarray(ids, jnp.int64).reshape(-1)
        self.vals = jnp.asarray(vals).reshape(
            (self.ids.shape[0],) + tuple(shape[1:]))
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return self.vals.dtype

    def astype(self, dtype):
        return _RspCot(self.ids, self.vals.astype(dtype), self.shape)

    def to_dense(self):
        return jnp.zeros(self.shape, self.vals.dtype).at[self.ids].add(
            self.vals)

    def __add__(self, other):
        if isinstance(other, _RspCot):
            return _RspCot(jnp.concatenate([self.ids, other.ids]),
                           jnp.concatenate([self.vals, other.vals]),
                           self.shape)
        return self.to_dense() + other

    __radd__ = __add__


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """shape (N, ...) with only rows `indices` stored in `values`.

    Rows-only storage is the source of truth; `_data` (the dense view
    the base NDArray API is written against) is a lazy, uncached
    materialization — constructing or updating a RowSparseNDArray never
    allocates O(N) memory."""

    __slots__ = ("_indices", "_values", "_shape")

    def __init__(self, indices, values, shape, ctx=None, _dedup=True):
        values = jnp.asarray(values)
        if _dedup:
            indices, values = _dedup_rows(indices, values)
        else:
            indices = jnp.asarray(indices, jnp.int64).reshape(-1)
        self._indices = indices
        self._values = values
        self._shape = tuple(int(s) for s in shape)
        # NDArray.__init__ not called: it would store a dense buffer.
        self._ctx = ctx or current_context()
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._writable = True
        self._base = None

    # -- rows-only accessors --------------------------------------------
    @property
    def _data(self):
        """Lazy dense view (NOT cached — peak memory stays O(nnz) unless
        a dense sink is actually used)."""
        return jnp.zeros(self._shape, self._values.dtype).at[
            self._indices].add(self._values)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return _np.dtype(self._values.dtype)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices, self._ctx)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, self._ctx)

    # -- mutation (in-place row assignment keeps object identity for
    #    Parameter._grad / kvstore out= contracts) ----------------------
    def _set_data(self, new_data) -> None:
        raise MXNetError(
            "RowSparseNDArray has rows-only storage; use _assign_rows / "
            "_add_rows (or tostype('default') for a dense copy)")

    def _assign_rows(self, indices, values) -> None:
        indices, values = _dedup_rows(indices, values)
        self._indices = indices
        self._values = values
        self._version += 1

    def _add_rows(self, indices, values) -> None:
        self._assign_rows(jnp.concatenate([self._indices,
                                           jnp.asarray(indices, jnp.int64)
                                           .reshape(-1)]),
                          jnp.concatenate([self._values,
                                           jnp.asarray(values)]))

    def _upsert_rows(self, indices, values) -> None:
        """Replace the listed rows (insert if absent), keeping all other
        stored rows — the write-back half of a rows-only optimizer step
        (parity: optimizer_op.cc SGDUpdateRspRspImpl writes only touched
        rows).  `indices` must be unique; O(nnz) host index plumbing."""
        idx = _np.asarray(indices).astype(_np.int64).ravel()
        have = _np.asarray(self._indices)
        keep = ~_np.isin(have, idx)
        ids = _np.concatenate([have[keep], idx])
        kept_vals = jnp.take(self._values,
                             jnp.asarray(_np.where(keep)[0]), axis=0)
        vals = jnp.concatenate([kept_vals, jnp.asarray(values)])
        order = _np.argsort(ids, kind="stable")
        self._indices = jnp.asarray(ids[order], jnp.int64)
        self._values = jnp.take(vals, jnp.asarray(order), axis=0)
        self._version += 1

    def _clear_rows(self) -> None:
        self._indices = jnp.zeros((0,), jnp.int64)
        self._values = jnp.zeros((0,) + self._shape[1:], self._values.dtype)
        self._version += 1

    def wait_to_read(self) -> None:
        if hasattr(self._values, "block_until_ready"):
            self._values.block_until_ready()

    wait_to_write = wait_to_read

    def copy(self):
        return RowSparseNDArray(self._indices, self._values, self._shape,
                                self._ctx, _dedup=False)

    def tostype(self, stype):
        if stype == "row_sparse":
            # fresh array: rsp arrays mutate in place (_assign_rows), so
            # returning self would alias source and result
            return self.copy()
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def retain(self, indices):
        """Keep only the intersection with `indices` (parity:
        sparse_retain-inl.h) — O(nnz + len(indices)), never dense."""
        idx = _np.unique(_np.asarray(
            indices.asnumpy() if isinstance(indices, NDArray)
            else indices).astype(_np.int64).ravel())
        have = _np.asarray(self._indices)
        mask = _np.isin(idx, have)
        kept = idx[mask]
        pos = _np.searchsorted(have, kept)
        vals = jnp.take(self._values, jnp.asarray(pos), axis=0)
        return RowSparseNDArray(kept, vals, self._shape, self._ctx,
                                _dedup=False)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {'x'.join(map(str, self._shape))} "
                f"({self._indices.shape[0]} rows) @{self._ctx}>")


def _host_row_ids(indptr_np, n_rows):
    """Per-nonzero row id from host indptr fenceposts (the one shared
    expansion — device-side twin: _csr_row_ids)."""
    return _np.repeat(_np.arange(n_rows), _np.diff(indptr_np))


class CSRNDArray(BaseSparseNDArray):
    """2-D (M, N) compressed-sparse-row; nnz-only storage, lazy dense."""

    __slots__ = ("_indptr", "_indices_c", "_values", "_shape",
                 "_host_triplet")

    def __init__(self, data, indptr, indices, shape, ctx=None):
        # batches built from host data (LibSVMIter) keep the numpy
        # triplet so the copyto feed path never downloads device arrays
        # just to re-upload them padded
        self._host_triplet = (data, indptr, indices) if all(
            isinstance(a, _np.ndarray) for a in (data, indptr, indices)) \
            else None
        self._indptr = jnp.asarray(indptr, jnp.int64)
        self._indices_c = jnp.asarray(indices, jnp.int64)
        self._values = jnp.asarray(data)
        self._shape = tuple(int(s) for s in shape)
        self._ctx = ctx or current_context()
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._writable = True
        self._base = None

    @property
    def _data(self):
        """Lazy dense view (uncached)."""
        rows = _host_row_ids(_np.asarray(self._indptr), self._shape[0])
        return jnp.zeros(self._shape, self._values.dtype).at[
            jnp.asarray(rows), self._indices_c].add(self._values)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return _np.dtype(self._values.dtype)

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._indptr, self._ctx)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices_c, self._ctx)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, self._ctx)

    def _set_data(self, new_data) -> None:
        raise MXNetError("CSRNDArray has nnz-only storage; build a new one "
                         "or use tostype('default') for a dense copy")

    def wait_to_read(self) -> None:
        if hasattr(self._values, "block_until_ready"):
            self._values.block_until_ready()

    wait_to_write = wait_to_read

    def copy(self):
        return CSRNDArray(self._values, self._indptr, self._indices_c,
                          self._shape, self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self.copy()
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cannot convert csr to {stype}")

    def copyto(self, other):
        """Feed a dense buffer from csr storage with an O(nnz) transfer:
        upload the nnz triplet (values, row-ids, cols — padded to a
        power-of-two bucket so recompiles stay bounded) and scatter to
        dense ON THE TARGET DEVICE.  This is the Module batch-feed path
        for LibSVM-style csr data (`_load_arg` -> `arr.copyto(tgt)`):
        through a thin host<->device link the dense upload is O(B·F)
        while the batch's information is O(nnz) — same lever as
        ImageRecordIter(device_augment=True).  Mesh-sharded targets and
        non-dense destinations keep the base dense behavior."""
        from ..context import Context
        if isinstance(other, Context) or isinstance(other, BaseSparseNDArray) \
                or getattr(other, "ndim", None) is None \
                or tuple(other.shape) != self._shape \
                or getattr(other._data, "sharding", None) is not None \
                and len(other._data.sharding.device_set) > 1:
            return NDArray.copyto(self, other)
        nnz = int(self._values.shape[0])
        bucket = max(16, 1 << (nnz - 1).bit_length()) if nnz else 16
        vals = _np.zeros(bucket, _np.dtype(self._values.dtype))
        rows = _np.zeros(bucket, _np.int32)
        cols = _np.zeros(bucket, _np.int32)
        if nnz:
            if self._host_triplet is not None:
                hvals, hindptr, hcols = self._host_triplet
            else:  # device-built csr: one download of the O(nnz) triplet
                hvals, hindptr, hcols = (_np.asarray(self._values),
                                         _np.asarray(self._indptr),
                                         _np.asarray(self._indices_c))
            vals[:nnz] = hvals
            rows[:nnz] = _host_row_ids(hindptr,
                                       self._shape[0]).astype(_np.int32)
            cols[:nnz] = hcols
        dev = other._data.devices().pop() if hasattr(other._data, "devices") \
            else None
        # eager sp-op staging: the scatter inputs are transient (dead
        # once `dense` exists); the retained output is ledger-tracked
        # through other._set_data
        # graft-lint: disable=memory-hygiene
        put = (lambda a: _jax.device_put(a, dev)) if dev is not None \
            else jnp.asarray
        dense = _csr_scatter_dense(put(vals), put(rows), put(cols),
                                   self._shape,
                                   _np.dtype(other.dtype).name)
        other._set_data(dense)
        return other

    def __repr__(self):
        return (f"\n<CSRNDArray {'x'.join(map(str, self._shape))} "
                f"({self._values.shape[0]} nnz) @{self._ctx}>")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create RowSparseNDArray from (data, indices) tuple or dense source."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        values = _np.asarray(values.asnumpy() if isinstance(values, NDArray)
                             else values)
        indices = _np.asarray(indices.asnumpy()
                              if isinstance(indices, NDArray) else indices)
        if dtype is not None:
            values = values.astype(np_dtype(dtype))
        return RowSparseNDArray(indices, values, shape, ctx)
    if isinstance(arg1, RowSparseNDArray):
        # fresh array: rsp arrays are mutated in place (_assign_rows), so
        # returning arg1 itself would alias source and result
        return arg1.copy()
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    if dtype is not None:
        dense = dense.astype(np_dtype(dtype))
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(nz, dense[nz], dense.shape, ctx, _dedup=False)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(_np.asarray(data), _np.asarray(indptr),
                          _np.asarray(indices), shape, ctx)
    if isinstance(arg1, CSRNDArray):
        return arg1.copy()
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    if dtype is not None:
        dense = dense.astype(np_dtype(dtype))
    sp_rows, sp_cols = _np.nonzero(dense)
    order = _np.lexsort((sp_cols, sp_rows))
    sp_rows, sp_cols = sp_rows[order], sp_cols[order]
    indptr = _np.zeros(dense.shape[0] + 1, _np.int64)
    _np.add.at(indptr, sp_rows + 1, 1)
    indptr = _np.cumsum(indptr)
    return CSRNDArray(dense[sp_rows, sp_cols], indptr, sp_cols,
                      dense.shape, ctx)


def cast_storage(arr: NDArray, stype: str):
    """Parity: src/operator/tensor/cast_storage.cc — REAL storage
    conversion at the NDArray layer (dense<->rsp/csr); the symbol-space
    twin (ops/sparse_ops.py) stays value-level because storage classes
    do not exist inside an XLA graph."""
    cur = getattr(arr, "stype", "default")
    if stype == cur:
        # always a fresh array — sparse arrays mutate in place, so a
        # passthrough would alias source and result
        return NDArray(arr._data, arr._ctx) if stype == "default" \
            else arr.copy()
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError(f"unknown stype {stype}")


def gather_rows(arr, rows):
    """arr[rows] as a stacked block WITHOUT densifying rsp storage; rows
    absent from an rsp array read as zero (parity: kvstore_local.h
    PullRowSparse).  Shared by KVStore.row_sparse_pull and the rows-only
    optimizer step."""
    if isinstance(arr, RowSparseNDArray):
        have = _np.asarray(arr._indices)
        idx = _np.asarray(rows)
        if len(have) == 0:
            return jnp.zeros((len(idx),) + arr.shape[1:],
                             arr._values.dtype)
        pos = _np.searchsorted(have, idx)
        posc = _np.clip(pos, 0, len(have) - 1)
        hit = (pos < len(have)) & (have[posc] == idx)
        out = jnp.take(arr._values, jnp.asarray(posc), axis=0)
        return jnp.where(
            jnp.asarray(hit).reshape((-1,) + (1,) * (out.ndim - 1)),
            out, jnp.zeros((), out.dtype))
    return jnp.take(arr._data, jnp.asarray(rows), axis=0)


def retain(data, indices):
    """Keep only the listed rows (parity: sparse_retain-inl.h; module-level
    twin of RowSparseNDArray.retain)."""
    if isinstance(data, RowSparseNDArray):
        return data.retain(indices)
    from .register import _gen
    idx = indices if isinstance(indices, NDArray) else array(indices)
    return _gen.sparse_retain(data, idx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (parity: src/operator/tensor/dot-inl.h CSR×dense
    forms).  CSR lhs takes the O(nnz·N) storage-dispatch path below
    (`_dot_sparse_ex`); other sparse operand combinations fall back to
    the dense MXU lowering (documented perf cliff, SURVEY.md §7)."""
    from .register import _gen
    return _gen.dot(lhs, rhs, transpose_a=transpose_a,
                    transpose_b=transpose_b)


# ---------------------------------------------------------------------------
# nnz-path CSR dot (parity: src/operator/tensor/dot-inl.h DotCsrDnsDns /
# DotCsrDnsRspImpl; dispatch parity: DispatchMode::kFComputeEx,
# src/imperative/imperative.cc:37-65).  O(nnz·N) work instead of
# O(M·K·N): per-nonzero gather of the dense rows, scaled, scatter-added
# — the dense (M,K) form of the csr operand never exists.
# ---------------------------------------------------------------------------
@_functools.partial(_jax.jit, static_argnums=(3, 4))
def _csr_scatter_dense(vals, rows, cols, shape, dtype):
    """Padded nnz triplet -> dense, on whatever device the inputs live
    (CSRNDArray.copyto's O(nnz)-transfer feed).  Pad slots carry value
    0 at (0, 0) — additive no-ops."""
    return jnp.zeros(shape, dtype).at[rows, cols].add(
        vals.astype(dtype))


def _csr_row_ids(indptr, nnz):
    """Per-nonzero row id from the indptr fenceposts (device, jittable)."""
    return jnp.searchsorted(indptr, jnp.arange(nnz, dtype=indptr.dtype),
                            side="right") - 1


@_functools.partial(_jax.jit, static_argnums=(4,))
def _csr_mm(vals, indptr, cols, rhs, n_rows):
    """dense(M,N) = csr(M,K) · dense(K,N)."""
    row_ids = _csr_row_ids(indptr, vals.shape[0])
    contrib = jnp.take(rhs, cols, axis=0, mode="clip") * vals[:, None]
    out_dtype = jnp.result_type(vals.dtype, rhs.dtype)
    return jnp.zeros((n_rows, rhs.shape[1]), out_dtype).at[row_ids].add(
        contrib.astype(out_dtype))


@_jax.jit
def _csr_t_rows(vals, indptr, cols, rhs):
    """Per-nonzero rows of csr(M,K)ᵀ · dense(M,N), keyed by column id:
    row r of the result = Σ_{nnz in col r} v·rhs[row].  The caller wraps
    (cols, rows) in a RowSparseNDArray / _RspCot; duplicate column ids
    segment-sum in the dedup."""
    row_ids = _csr_row_ids(indptr, vals.shape[0])
    return jnp.take(rhs, row_ids, axis=0, mode="clip") * vals[:, None]


def _grad_wanted(a):
    """A sparse operand gets a gradient only when one is attached to it
    (reference parity: sparse tensors are terminal data/feature inputs;
    the dense-lowered grad is computed on demand, not by default)."""
    return (getattr(a, "_grad", None) is not None
            and getattr(a, "_grad_req", "null") != "null")


def _dot_use_nnz(nnz, m, k, n, itemsize):
    """Path choice for csr·dense (measured,
    benchmark/python/sparse/sparse_bench.py): the nnz path builds an
    (nnz, N) gather intermediate; the dense path materializes the (M, K)
    lhs and rides the MXU, which wins by ~100x at 10% density.  Take nnz
    only when its intermediate is smaller than the dense form
    (true-sparse regime — e.g. libsvm features with N=1..small) or when
    densifying is infeasible at this dtype.  MXNET_SPARSE_DOT=nnz|dense
    overrides (tests pin storage behavior; the benchmark A/Bs both)."""
    mode = os.environ.get("MXNET_SPARSE_DOT", "auto")
    if mode in ("nnz", "dense"):
        return mode == "nnz"
    return nnz * n < m * k or m * k * itemsize > (1 << 31)


def _dot_sparse_ex(op, inputs, params, out):
    """Eager storage-dispatch executor for `dot` with sparse operands."""
    from .. import autograd

    lhs, rhs = inputs[0], inputs[1]
    ta = bool(params.get("transpose_a", False))
    tb = bool(params.get("transpose_b", False))
    recording = autograd.is_recording() and op.differentiable

    nnz_path = (isinstance(lhs, CSRNDArray)
                and not isinstance(rhs, BaseSparseNDArray)
                and getattr(rhs, "ndim", None) == 2)
    if not nnz_path:
        # remaining stype combinations: decline — invoke() continues its
        # normal dense lowering (documented perf cliff) with profiler
        # events, out= handling, and recording against the original
        # operands, so an attached grad on a sparse input still arrives
        return NotImplemented

    vals, indptr, cols = lhs._values, lhs._indptr, lhs._indices_c
    M, K = lhs.shape
    B = rhs._data.T if tb else rhs._data
    N = int(B.shape[1])
    nnz = int(vals.shape[0])
    out_dtype = jnp.result_type(vals.dtype, B.dtype)

    use_nnz = _dot_use_nnz(nnz, M, K, N,
                           _np.dtype(out_dtype).itemsize)

    if ta:
        # dot(csrᵀ, dense) -> row_sparse (reference output-stype inference:
        # DotCsrDnsRspImpl) with rows = the csr's occupied columns
        if nnz == 0:
            res = zeros_sparse("row_sparse", (K, N), lhs._ctx, out_dtype)
        else:
            res = RowSparseNDArray(
                cols, _csr_t_rows(vals, indptr, cols, B).astype(out_dtype),
                (K, N), lhs._ctx)
    else:
        A_dense = None  # densified ONCE here, shared with the vjp below
        if nnz == 0:
            data = jnp.zeros((M, N), out_dtype)
        elif use_nnz:
            data = _csr_mm(vals, indptr, cols, B, M)
        else:
            A_dense = lhs._data.astype(out_dtype)
            data = jnp.matmul(A_dense, B.astype(out_dtype))
        res = NDArray(data, lhs._ctx)

    if out is not None:
        if isinstance(out, RowSparseNDArray) and \
                isinstance(res, RowSparseNDArray):
            out._assign_rows(res._indices, res._values)
        elif not isinstance(out, BaseSparseNDArray):
            # dense out= is well-defined for either result stype
            out._set_data(res._data.astype(out.dtype))
        else:
            raise MXNetError("dot(csr, ...): out= storage type mismatch "
                             f"({type(out).__name__} vs {type(res).__name__})")
        res = out

    if recording:
        rshape = tuple(rhs.shape)
        # grad w.r.t. the csr operand is dense (M,K) — only computed when
        # the caller attached a grad buffer to it
        want_lhs = _grad_wanted(lhs)
        B_cap = B if want_lhs else None
        # dense-regime forward keeps the backward dense too, reusing the
        # forward's one densification (A_dense is None on the ta path)
        A_cap = None if ta else A_dense

        def vjp_fn(cots, _v=vals, _ip=indptr, _c=cols, _ta=ta, _tb=tb,
                   _rs=rshape, _M=M, _B=B_cap, _A=A_cap):
            cot = cots[0]  # dense, out-shaped (rsp heads densify upstream)
            if _ta:
                # out = Aᵀ·B: grad_B = A·cot, dense (M,N); with tb the
                # effective B was rhsᵀ, so transpose back to rhs layout
                g = _csr_mm(_v, _ip, _c, cot, _M)
                if _tb:
                    g = g.T
                g_lhs = None if _B is None else jnp.matmul(_B, cot.T)
            else:
                # out = A·B(ᵀ): grad_B = Aᵀ·cot.  nnz regime: rows-only
                # on the csr's columns (an _RspCot through the tape,
                # dense only at an explicit dense deposit); dense
                # regime: one MXU matmul on the captured lhs.
                if _A is not None:
                    g = jnp.matmul(_A.T, cot)
                elif _tb:
                    rows = _csr_t_rows(_v, _ip, _c, cot)
                    g = jnp.zeros((_rs[1], cot.shape[1]),
                                  rows.dtype).at[_c].add(rows)
                else:
                    g = _RspCot(_c, _csr_t_rows(_v, _ip, _c, cot), _rs)
                if _tb and not isinstance(g, _RspCot):
                    g = g.T
                g_lhs = None if _B is None else jnp.matmul(cot, _B.T)
            return (g_lhs, g)

        autograd._record(op, [lhs if want_lhs else None, rhs], [res],
                         vjp_fn, (res,))
    return res


from .register import register_sparse_ex as _register_sparse_ex  # noqa: E402

_register_sparse_ex("dot")(_dot_sparse_ex)


def zeros_sparse(stype, shape, ctx=None, dtype=None):
    if stype == "row_sparse":
        return RowSparseNDArray(_np.zeros((0,), _np.int64),
                                _np.zeros((0,) + tuple(shape[1:]),
                                          np_dtype(dtype)),
                                shape, ctx, _dedup=False)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,), np_dtype(dtype)),
                          _np.zeros((shape[0] + 1,), _np.int64),
                          _np.zeros((0,), _np.int64), shape, ctx)
    return zeros(shape, ctx=ctx, dtype=dtype)


zeros = zeros_sparse
