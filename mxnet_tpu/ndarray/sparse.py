"""Sparse NDArrays: row_sparse + csr (parity: python/mxnet/ndarray/sparse.py,
include/mxnet/ndarray.h:61-63, src/operator/tensor/cast_storage / dot sparse).

XLA has no first-class sparsity (SURVEY.md §7 risks), so these keep the
reference's *API and storage layout* (indices/values, indptr/indices/data)
while compute lowers to dense-segment gather/scatter — correct semantics,
documented perf cliff.  row_sparse is the path gluon sparse embeddings and
kvstore row_sparse_pull use.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import current_context
from .ndarray import NDArray, array, zeros


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """shape (N, ...) with only rows `indices` stored in `data`."""

    __slots__ = ("_indices", "_values", "_shape")

    def __init__(self, indices, values, shape, ctx=None):
        self._indices = jnp.asarray(indices, jnp.int64)
        self._values = jnp.asarray(values)
        self._shape = tuple(shape)
        dense = jnp.zeros(shape, self._values.dtype).at[self._indices].set(self._values)
        super().__init__(dense, ctx or current_context())

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices, self._ctx)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def retain(self, indices):
        idx = jnp.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices,
                          jnp.int64)
        vals = jnp.take(self._data, idx, axis=0)
        return RowSparseNDArray(idx, vals, self._shape, self._ctx)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {'x'.join(map(str, self._shape))} "
                f"({len(self._indices)} rows) @{self._ctx}>")


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("_indptr", "_indices_c", "_values", "_shape")

    def __init__(self, data, indptr, indices, shape, ctx=None):
        self._indptr = jnp.asarray(indptr, jnp.int64)
        self._indices_c = jnp.asarray(indices, jnp.int64)
        self._values = jnp.asarray(data)
        self._shape = tuple(shape)
        dense = _np.zeros(shape, _np.asarray(self._values).dtype)
        ip = _np.asarray(self._indptr)
        ic = _np.asarray(self._indices_c)
        vv = _np.asarray(self._values)
        for r in range(shape[0]):
            dense[r, ic[ip[r]:ip[r + 1]]] = vv[ip[r]:ip[r + 1]]
        super().__init__(jnp.asarray(dense), ctx or current_context())

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._indptr, self._ctx)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices_c, self._ctx)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError(f"cannot convert csr to {stype}")

    def __repr__(self):
        return (f"\n<CSRNDArray {'x'.join(map(str, self._shape))} "
                f"({len(self._values)} nnz) @{self._ctx}>")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create RowSparseNDArray from (data, indices) tuple or dense source."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        values = _np.asarray(values.asnumpy() if isinstance(values, NDArray) else values)
        indices = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices)
        if dtype is not None:
            values = values.astype(np_dtype(dtype))
        return RowSparseNDArray(indices, values, shape, ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(nz, dense[nz], dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(_np.asarray(data), _np.asarray(indptr),
                          _np.asarray(indices), shape, ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    if dtype is not None:
        dense = dense.astype(np_dtype(dtype))
    import numpy as np
    indptr = [0]
    indices = []
    data = []
    for r in range(dense.shape[0]):
        cols = np.where(dense[r] != 0)[0]
        indices.extend(cols.tolist())
        data.extend(dense[r, cols].tolist())
        indptr.append(len(indices))
    return CSRNDArray(np.asarray(data, dense.dtype), np.asarray(indptr),
                      np.asarray(indices), dense.shape, ctx)


def cast_storage(arr: NDArray, stype: str):
    """Parity: src/operator/tensor/cast_storage.cc."""
    cur = getattr(arr, "stype", "default")
    if stype == cur:
        # dense→default returns a fresh wrapper (callers may mutate it);
        # same-stype sparse arrays pass through (treated as immutable)
        return NDArray(arr._data, arr._ctx) if stype == "default" else arr
    if stype == "default":
        return NDArray(arr._data, arr._ctx)
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError(f"unknown stype {stype}")


def retain(data, indices):
    """Keep only the listed rows (parity: sparse_retain-inl.h; module-level
    twin of RowSparseNDArray.retain)."""
    if isinstance(data, RowSparseNDArray):
        return data.retain(indices)
    from .register import _gen
    idx = indices if isinstance(indices, NDArray) else array(indices)
    return _gen.sparse_retain(data, idx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (parity: dot-inl.h CSR×dense forms) — dense-backed
    lowering onto the MXU; storage classes accepted on either side."""
    from .register import _gen
    return _gen.dot(lhs, rhs, transpose_a=transpose_a,
                    transpose_b=transpose_b)


def zeros_sparse(stype, shape, ctx=None, dtype=None):
    if stype == "row_sparse":
        return RowSparseNDArray(_np.zeros((0,), _np.int64),
                                _np.zeros((0,) + tuple(shape[1:]), np_dtype(dtype)),
                                shape, ctx)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,), np_dtype(dtype)),
                          _np.zeros((shape[0] + 1,), _np.int64),
                          _np.zeros((0,), _np.int64), shape, ctx)
    return zeros(shape, ctx=ctx, dtype=dtype)


zeros = zeros_sparse
