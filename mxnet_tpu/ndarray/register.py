"""Eager op invocation + mechanical `nd.*` function generation.

Reference parity: `python/mxnet/ndarray/register.py:31-47` (op autogen from
the registry) + `src/c_api/c_api_ndarray.cc:80-142` (MXImperativeInvokeEx) +
`src/imperative/imperative.cc:86` (Imperative::Invoke).  One function per
registered op is synthesized into the `_gen` namespace; autograd recording
happens here (parity: Imperative::RecordOp, imperative.cc:182).
"""
from __future__ import annotations

import sys
import types
from typing import Optional

import jax
import time as _time
import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import Context, current_context
from ..ops import registry as _reg
from .. import profiler as _profiler
from .ndarray import NDArray, _place


def _canon_kwargs(kwargs):
    out = {}
    for k, v in kwargs.items():
        if k in ("dtype",) and v is not None and not isinstance(v, str):
            v = _np.dtype(v).name
        if isinstance(v, Context):
            v = str(v)
        out[k] = v
    return out


# storage-type dispatch (parity: DispatchMode::kFComputeEx picked in
# Imperative::InvokeOp, src/imperative/imperative.cc:37-65): an op with a
# registered sparse executor receives the NDArray OBJECTS (nnz storage
# intact) when any input is sparse, instead of the default dense `_data`
# lowering.  Handlers: fn(op, ndarray_inputs, params, out) -> result(s).
_SPARSE_EX = {}


def register_sparse_ex(op_name):
    def deco(fn):
        _SPARSE_EX[op_name] = fn
        return fn
    return deco


def invoke(op_name: str, ndarray_inputs, kwargs, out=None):
    """Execute a registered op eagerly on NDArrays; records on the autograd tape."""
    op = _reg.get_op(op_name)
    kwargs = dict(kwargs)
    kwargs.pop("name", None)
    ctx = kwargs.pop("ctx", None)
    if isinstance(ctx, str):
        dt, _, di = ctx.partition("(")
        ctx = Context(dt, int(di.rstrip(")")) if di else 0)
    kwargs.pop("num_args", None) if not any(
        a.name == "num_args" for a in op.schema.args.values()) else None
    if op.variadic and "num_args" in {a.name for a in op.schema.args.values()}:
        kwargs.setdefault("num_args", len(ndarray_inputs))
    params = dict(op.normalize(_canon_kwargs(kwargs)))

    from .. import autograd, random as _random

    if op_name in _SPARSE_EX:
        from .sparse import BaseSparseNDArray
        if any(isinstance(a, BaseSparseNDArray) for a in ndarray_inputs):
            res = _SPARSE_EX[op_name](op, ndarray_inputs, params, out)
            # NotImplemented = handler declined (unsupported stype combo);
            # fall through to the dense lowering below (parity: storage
            # fallback, src/executor/attach_op_execs_pass.cc:49-226)
            if res is not NotImplemented:
                return res

    if op.takes_is_train:
        params["__is_train__"] = autograd.is_training()
    params_t = tuple(sorted(params.items()))

    # None marks an omitted optional input: its slot still exists in the
    # op fn / vjp signature (empty pytree through jit), keeping grad
    # indices aligned for the inputs that are present
    raw = [None if a is None else
           (a._data if isinstance(a, NDArray) else jax.numpy.asarray(a))
           for a in ndarray_inputs]
    if op.needs_rng:
        raw.append(_random.next_key())

    recording = autograd.is_recording() and op.differentiable and op.mutates_input is None
    vjp_fn = None
    profiling = _profiler.is_recording()
    t0 = _time.perf_counter_ns() if profiling else 0
    if recording and op_name == "Embedding" and params.get("sparse_grad"):
        # rows-only weight gradient (parity: rsp embedding grad,
        # src/operator/tensor/indexing_op.h SparseEmbedding backward):
        # the vjp never scatters into an O(vocab) dense buffer — it
        # returns a row-sparse cotangent marker (token ids, per-token
        # cotangent rows) that flows through the tape and deposits into
        # the parameter's RowSparseNDArray grad
        outs = _reg.apply_op(op, params_t, raw)
        ids_raw, wshape = raw[0], raw[1].shape

        def vjp_fn(cots, _ids=ids_raw, _ws=wshape):
            from .sparse import _RspCot
            cot = cots[0]
            return (None, _RspCot(jax.numpy.ravel(_ids),
                                  cot.reshape((-1,) + tuple(_ws[1:])),
                                  _ws))
    elif recording:
        outs, vjp_fn = _reg.make_vjp(op, params_t, raw)
    else:
        outs = _reg.apply_op(op, params_t, raw)
    if profiling:
        # parity: OprExecStat recorded around kernel exec
        # (threaded_engine.h:324); async dispatch means this times
        # trace+enqueue, with device detail in the xplane trace
        t1 = _time.perf_counter_ns()
        _profiler.record_event(op_name, t0 / 1e3, t1 / 1e3)

    first_nd = next((a for a in ndarray_inputs if isinstance(a, NDArray)),
                    None)
    out_ctx = first_nd._ctx if first_nd is not None else (
        ctx or current_context())

    n_vis = len(outs) - len(op.aux_inputs)
    visible = outs[:n_vis]
    # write updated aux values back into their NDArrays (BatchNorm moving
    # stats, optimizer states) — parity: mutable aux_states/engine write vars
    for i, aux_idx in enumerate(op.aux_inputs):
        # aux omitted in an eager call (op fn defaulted it) — nothing to
        # write back into
        if aux_idx >= len(ndarray_inputs):
            continue
        tgt = ndarray_inputs[aux_idx]
        if isinstance(tgt, NDArray):
            tgt._set_data(outs[n_vis + i])
    if op.mutates_input is not None:
        tgt = ndarray_inputs[op.mutates_input]
        tgt._set_data(visible[0])
        results = [tgt] + [NDArray(o, out_ctx) for o in visible[1:]]
    else:
        results = [NDArray(o, out_ctx) for o in visible]
        if ctx is not None and not ndarray_inputs:
            results = [r.as_in_context(ctx) for r in results]

    if out is not None:
        outs_tgt = out if isinstance(out, (list, tuple)) else [out]
        for tgt, res in zip(outs_tgt, results):
            tgt._set_data(res._data.astype(tgt.dtype))
        results = list(outs_tgt)

    if recording:
        autograd._record(op, ndarray_inputs, results, vjp_fn, outs)

    if len(results) == 1:
        return results[0]
    return results


def _make_nd_func(op_name: str):
    op = _reg.get_op(op_name)

    def fn(*args, out=None, **kwargs):
        inputs = []
        rest = list(args)
        # positional NDArrays are inputs; positional scalars map onto params
        while rest and isinstance(rest[0], (NDArray, _np.ndarray, list, tuple)) \
                and not (op.input_names == () or
                         (not op.variadic and len(inputs) >= len(op.input_names))):
            a = rest.pop(0)
            if not isinstance(a, NDArray):
                from .ndarray import array as _array
                a = _array(a)
            inputs.append(a)
        if rest:
            # leftover positional args map to schema args in declared order
            names = [a for a in op.schema.args]
            taken = [n for n in names if n not in kwargs]
            for v, n in zip(rest, taken):
                kwargs[n] = v
        # tensor inputs passed by keyword (e.g. optional lengths inputs):
        # place them at their declared slot, padding skipped slots w/ None
        if not op.variadic:
            for i, n in enumerate(op.input_names):
                if n in kwargs and (kwargs[n] is None or
                                    isinstance(kwargs[n],
                                               (NDArray, _np.ndarray,
                                                list, tuple))):
                    v = kwargs.pop(n)
                    if v is not None and not isinstance(v, NDArray):
                        from .ndarray import array as _array
                        v = _array(v)
                    while len(inputs) < i:
                        inputs.append(None)
                    if len(inputs) == i:
                        inputs.append(v)
                    else:
                        inputs[i] = v
        return invoke(op_name, inputs, kwargs, out=out)

    fn.__name__ = op_name
    fn.__qualname__ = op_name
    fn.__doc__ = op.docstring or f"Autogenerated wrapper for operator '{op_name}'."
    return fn


def populate(module) -> None:
    """Generate one function per op into `module` (parity: register.py autogen)."""
    for name in list(_reg.OP_REGISTRY) + list(_reg.OP_ALIASES):
        setattr(module, name, _make_nd_func(name))


_gen = types.ModuleType("mxnet_tpu.ndarray._gen")
populate(_gen)
sys.modules["mxnet_tpu.ndarray._gen"] = _gen


def _late_attach(op_name):
    """Frontend hook (registry.FRONTEND_ATTACH_HOOKS): expose an op
    registered after import on mx.nd immediately."""
    f = _make_nd_func(op_name)
    setattr(_gen, op_name, f)
    pkg = sys.modules.get("mxnet_tpu.ndarray")
    if pkg is not None and not hasattr(pkg, op_name):
        setattr(pkg, op_name, f)


_reg.FRONTEND_ATTACH_HOOKS.append(_late_attach)
