"""`mx.nd.random` namespace (parity: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from . import register
from .register import invoke
from .ndarray import NDArray


def _sample(opname, tensor_params, kwargs, positional):
    """Dispatch to scalar-parameter _random_* or tensor-parameter _sample_*."""
    inputs = [v for v in positional if isinstance(v, NDArray)]
    if inputs:
        kw = {k: v for k, v in kwargs.items() if k not in tensor_params}
        return invoke("_sample" + opname, inputs, kw, out=kwargs.get("out"))
    kw = dict(kwargs)
    for name, val in zip(tensor_params, positional):
        kw[name] = val
    out = kw.pop("out", None)
    return invoke("_random" + opname, [], kw, out=out)


def uniform(low=0, high=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _sample("_uniform", ("low", "high"),
                   dict(shape=shape, dtype=dtype, ctx=ctx, out=out), (low, high))


def normal(loc=0, scale=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    if isinstance(loc, NDArray):
        return invoke("_sample_normal", [loc, scale], dict(shape=shape, dtype=dtype))
    return invoke("_random_normal", [],
                  dict(loc=loc, scale=scale, shape=shape, dtype=dtype, ctx=ctx), out=out)


def randn(*shape, loc=0, scale=1, dtype="float32", ctx=None, **kw):
    return normal(loc, scale, shape or (1,), dtype=dtype, ctx=ctx)


def gamma(alpha=1, beta=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return invoke("_random_gamma", [],
                  dict(alpha=alpha, beta=beta, shape=shape, dtype=dtype, ctx=ctx), out=out)


def exponential(lam=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return invoke("_random_exponential", [],
                  dict(lam=lam, shape=shape, dtype=dtype, ctx=ctx), out=out)


def poisson(lam=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return invoke("_random_poisson", [],
                  dict(lam=lam, shape=shape, dtype=dtype, ctx=ctx), out=out)


def negative_binomial(k=1, p=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return invoke("_random_negative_binomial", [],
                  dict(k=k, p=p, shape=shape, dtype=dtype, ctx=ctx), out=out)


def generalized_negative_binomial(mu=1, alpha=1, shape=(), dtype="float32",
                                  ctx=None, out=None, **kw):
    return invoke("_random_generalized_negative_binomial", [],
                  dict(mu=mu, alpha=alpha, shape=shape, dtype=dtype, ctx=ctx), out=out)


def randint(low, high, shape=(), dtype="int32", ctx=None, out=None, **kw):
    return invoke("_random_randint", [],
                  dict(low=low, high=high, shape=shape, dtype=dtype, ctx=ctx), out=out)


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    return invoke("_sample_multinomial", [data],
                  dict(shape=shape, get_prob=get_prob, dtype=dtype))


def shuffle(data, **kw):
    return invoke("_shuffle", [data], {})
