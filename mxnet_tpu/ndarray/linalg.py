"""`mx.nd.linalg` namespace (parity: python/mxnet/ndarray/linalg.py over
src/operator/tensor/la_op.cc)."""
from .register import invoke


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, **kw):
    return invoke("linalg_gemm", [A, B, C],
                  dict(transpose_a=transpose_a, transpose_b=transpose_b,
                       alpha=alpha, beta=beta))


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, **kw):
    return invoke("linalg_gemm2", [A, B],
                  dict(transpose_a=transpose_a, transpose_b=transpose_b, alpha=alpha))


def potrf(A, **kw):
    return invoke("linalg_potrf", [A], {})


def potri(A, **kw):
    return invoke("linalg_potri", [A], {})


def trsm(A, B, transpose=False, rightside=False, alpha=1.0, **kw):
    return invoke("linalg_trsm", [A, B],
                  dict(transpose=transpose, rightside=rightside, alpha=alpha))


def trmm(A, B, transpose=False, rightside=False, alpha=1.0, **kw):
    return invoke("linalg_trmm", [A, B],
                  dict(transpose=transpose, rightside=rightside, alpha=alpha))


def sumlogdiag(A, **kw):
    return invoke("linalg_sumlogdiag", [A], {})


def syrk(A, transpose=False, alpha=1.0, **kw):
    return invoke("linalg_syrk", [A], dict(transpose=transpose, alpha=alpha))
