"""NDArray: the user-facing async tensor, backed by a jax.Array.

Reference parity: `include/mxnet/ndarray.h:79` + `src/ndarray/ndarray.cc` +
`python/mxnet/ndarray/ndarray.py:169`.  Design mapping:
  - ref-counted Chunk + engine var  →  an immutable jax.Array buffer; PJRT
    async dispatch gives the "returns immediately, syncs on read" semantics
    (WaitToRead == block_until_ready).
  - in-place mutation (a += b, a[:] = x, optimizer updates)  →  functional
    update producing a new buffer swapped into the wrapper (`_set_data`),
    with a version counter so the autograd tape sees writes.
  - CopyFromTo cross-device copy  →  jax.device_put.
  - save/load  →  same API (`mx.nd.save/load`), container format is a
    single-file archive of npy payloads (the reference's dmlc binary format
    is CUDA-era; docstring notes divergence).
"""
from __future__ import annotations

import builtins
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as _np

from ..analysis import sanitizer as _sanitizer
from ..base import MXNetError, np_dtype
from ..context import Context, current_context
from ..observability import memory as _memory
from .. import engine as _engine


class NDArray:
    """Multi-dimensional array on a device, with async execution semantics."""

    __slots__ = ("_data", "_ctx", "_version", "_grad", "_grad_req", "_writable",
                 "_base", "_fresh_grad", "__weakref__")
    # make numpy defer to our __r*__ ops
    __array_priority__ = 100.0

    def __init__(self, data, ctx: Optional[Context] = None, writable: bool = True):
        self._data = data
        self._ctx = ctx or current_context()
        self._version = 0
        self._grad: Optional["NDArray"] = None
        self._grad_req: str = "null"
        self._writable = writable
        self._base = None
        # set True by autograd.backward when it deposits into this array's
        # grad buffer; Trainer.step clears it after consuming the gradient
        # (parity: NDArray::fresh_out_grad, the stale-grad guard)
        self._fresh_grad = False
        # HBM ledger: track the wrapper (it survives _set_data swaps)
        # under the current memory_scope tag — one boolean test when
        # MXNET_MEMORY_LEDGER=0 (docs/memory.md)
        if _memory.ENABLED:
            _memory.register_nd(self)
        _engine.maybe_sync([data])

    # -- core accessors -----------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def handle(self):
        """The underlying jax.Array (the TPU analog of the C NDArrayHandle)."""
        return self._data

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        """Allocate gradient buffer for autograd (parity: ndarray.py attach_grad)."""
        self._grad = zeros(self.shape, ctx=self._ctx, dtype=self.dtype)
        self._grad_req = grad_req
        from .. import autograd
        autograd._mark_variable(self)

    # -- mutation -----------------------------------------------------------
    def _set_data(self, new_data) -> None:
        if not self._writable:
            raise MXNetError("cannot write to a read-only NDArray")
        self._data = new_data
        self._version += 1
        _engine.maybe_sync([new_data])

    # -- sync / export ------------------------------------------------------
    def wait_to_read(self) -> None:
        """Parity: NDArray::WaitToRead — block until the buffer is
        computed (via the engine, so the stall is metered)."""
        from .. import engine as _engine
        _engine.wait_for_var(self._data)

    wait_to_write = wait_to_read

    def asnumpy(self) -> _np.ndarray:
        """Copy to host numpy (the synchronization point, as in the
        reference).  Always a WRITABLE copy — jax device buffers surface as
        read-only views, but the reference contract (NDArray::SyncCopyToCPU)
        hands the caller an owned buffer (custom-op backwards mutate it)."""
        # sanitizer chokepoint: inside an analysis.no_sync() region this
        # raises (MXNET_SANITIZE=1); one flag test otherwise
        _sanitizer.check_sync("NDArray.asnumpy")
        out = _np.asarray(self._data)
        if not out.flags.writeable:
            out = out.copy()
        return out

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("asscalar requires size-1 array")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._data.__dlpack__(**kw)

    # -- conversion / copies ------------------------------------------------
    def astype(self, dtype, copy=True) -> "NDArray":
        dt = np_dtype(dtype)
        if not copy and dt == self.dtype:
            return self
        return NDArray(self._data.astype(dt), self._ctx)

    def copy(self) -> "NDArray":
        return NDArray(self._data + 0 if False else jnp.asarray(self._data), self._ctx)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()), other)
        # preserve the target's sharding (mesh-replicated params stay
        # replicated through kvstore pulls / set_params)
        tgt_sharding = getattr(other._data, "sharding", None)
        data = self._data.astype(other.dtype)
        if tgt_sharding is not None and \
                getattr(data, "sharding", None) == tgt_sharding:
            # already typed and placed: no transfer (keeps the training
            # hot path at 0 device_puts/step, tests/test_dispatch_count)
            other._set_data(data)
        else:
            placement = tgt_sharding if tgt_sharding is not None else \
                other._ctx.jax_device()
            other._set_data(jax.device_put(data, placement))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def detach(self) -> "NDArray":
        out = NDArray(self._data, self._ctx)
        return out

    def tostype(self, stype: str) -> "NDArray":
        if stype == "default":
            return self
        from . import sparse
        return sparse.cast_storage(self, stype)

    # -- shape views ---------------------------------------------------------
    # under autograd.record() these dispatch through the registered ops so
    # the tape sees them (reference parity: every view is an NNVM node);
    # outside recording they stay raw jnp views (no registry overhead)
    def _recording(self) -> bool:
        from .. import autograd
        return autograd.is_recording()

    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        if self._recording():
            from . import _gen
            return _gen.Reshape(self, shape=tuple(shape))
        from ..ops.matrix import infer_reshape
        return NDArray(jnp.reshape(self._data, infer_reshape(shape, self.shape)), self._ctx)

    def reshape_like(self, other) -> "NDArray":
        # other.shape is literal here — MXNet special codes (0 = copy dim)
        # apply only to user-passed reshape specs
        if self._recording() and all(d > 0 for d in other.shape):
            from . import _gen
            return _gen.Reshape(self, shape=tuple(other.shape))
        return NDArray(jnp.reshape(self._data, other.shape), self._ctx)

    def expand_dims(self, axis) -> "NDArray":
        if self._recording():
            from . import _gen
            return _gen.expand_dims(self, axis=axis)
        return NDArray(jnp.expand_dims(self._data, axis), self._ctx)

    def flatten(self) -> "NDArray":
        if self._recording():
            from . import _gen
            return _gen.Flatten(self)
        return NDArray(jnp.reshape(self._data, (self.shape[0], -1)), self._ctx)

    def squeeze(self, axis=None) -> "NDArray":
        if self._recording():
            from . import _gen
            return _gen.squeeze(self, axis=axis)
        return NDArray(jnp.squeeze(self._data, axis), self._ctx)

    def transpose(self, axes=None) -> "NDArray":
        if self._recording():
            from . import _gen
            return _gen.transpose(self, axes=axes)
        return NDArray(jnp.transpose(self._data, axes), self._ctx)

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    def broadcast_to(self, shape) -> "NDArray":
        if self._recording():
            from . import _gen
            return _gen.broadcast_to(self, shape=tuple(shape))
        return NDArray(jnp.broadcast_to(self._data, shape), self._ctx)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from . import _gen
        return _gen.split(self, num_outputs=num_outputs, axis=axis,
                          squeeze_axis=squeeze_axis)

    # -- indexing ------------------------------------------------------------
    def __getitem__(self, key):
        if self._recording():
            routed = self._getitem_recorded(key)
            if routed is not None:
                return routed
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        elif isinstance(key, tuple):
            key = tuple(k._data.astype(jnp.int32) if isinstance(k, NDArray) else k
                        for k in key)
        return NDArray(self._data[key], self._ctx)

    def _getitem_recorded(self, key):
        """Route tape-visible indexing through registered ops (int / slice /
        tuple-of-slices / integer-array); returns None for exotic keys
        (boolean masks etc.), which stay raw views."""
        from . import _gen
        if isinstance(key, NDArray):
            # wrap mode keeps numpy negative-index semantics (clip, the op
            # default, would clamp -1 to 0)
            return _gen.take(self, key, axis=0, mode="wrap")
        if isinstance(key, int):
            end = key + 1 if key != -1 else None
            return _gen.slice_axis(self, axis=0, begin=key,
                                   end=end).squeeze(axis=0)
        if isinstance(key, slice):
            if key.step in (None, 1):
                b, e, _ = key.indices(self.shape[0])
                return _gen.slice_axis(self, axis=0, begin=b, end=e)
            return None
        if isinstance(key, tuple) and all(
                isinstance(k, slice) and k.step in (None, 1) for k in key):
            idx = [k.indices(d) for k, d in zip(key, self.shape)]
            begin = tuple(b for b, _, _ in idx)
            end = tuple(e for _, e, _ in idx)
            return _gen.slice(self, begin=begin, end=end)
        return None

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, (int, float, bool)):
            v = value
        else:
            v = jnp.asarray(_np.asarray(value), dtype=self.dtype)
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        elif isinstance(key, tuple):
            key = tuple(k._data.astype(jnp.int32) if isinstance(k, NDArray) else k
                        for k in key)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            if not _np.isscalar(v):
                v = jnp.broadcast_to(v, self.shape).astype(self.dtype)
                self._set_data(jnp.asarray(v))
                return
        self._set_data(self._data.at[key].set(v))

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __len__(self) -> int:
        return self.shape[0]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    # -- arithmetic (dispatch through registered ops so autograd records) ----
    def _binary(self, other, op, scalar_op, rop=False):
        from . import _gen
        if isinstance(other, NDArray):
            a, b = (other, self) if rop else (self, other)
            return getattr(_gen, op)(a, b)
        if rop and not op.startswith("broadcast_"):
            return getattr(_gen, scalar_op)(self, scalar=float(other))
        return getattr(_gen, scalar_op)(self, scalar=float(other))

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_rminus_scalar", rop=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "_rdiv_scalar", rop=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "broadcast_mod", "_rmod_scalar", rop=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binary(o, "broadcast_power", "_rpower_scalar", rop=True)

    def __neg__(self):
        from . import _gen
        return _gen.negative(self)

    def __abs__(self):
        from . import _gen
        return _gen.abs(self)

    def __eq__(self, o):
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place ops mutate the buffer (parity: engine write-dependency ops)
    def __iadd__(self, o):
        res = self.__add__(o)
        self._set_data(res._data.astype(self.dtype))
        return self

    def __isub__(self, o):
        res = self.__sub__(o)
        self._set_data(res._data.astype(self.dtype))
        return self

    def __imul__(self, o):
        res = self.__mul__(o)
        self._set_data(res._data.astype(self.dtype))
        return self

    def __itruediv__(self, o):
        res = self.__truediv__(o)
        self._set_data(res._data.astype(self.dtype))
        return self

    __idiv__ = __itruediv__

    # -- reductions as methods ----------------------------------------------
    def sum(self, axis=None, keepdims=False, **kw):
        from . import _gen
        return _gen.sum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        from . import _gen
        return _gen.mean(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        from . import _gen
        return _gen.max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        from . import _gen
        return _gen.min(self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, **kw):
        from . import _gen
        return _gen.argmax(self, axis=axis)

    def argmin(self, axis=None, **kw):
        from . import _gen
        return _gen.argmin(self, axis=axis)

    def norm(self, **kw):
        from . import _gen
        return _gen.norm(self, **kw)

    def abs(self, **kw):
        from . import _gen
        return _gen.abs(self)

    def clip(self, a_min, a_max):
        from . import _gen
        return _gen.clip(self, a_min=a_min, a_max=a_max)

    def sqrt(self):
        from . import _gen
        return _gen.sqrt(self)

    def square(self):
        from . import _gen
        return _gen.square(self)

    def dot(self, other, **kw):
        from . import _gen
        return _gen.dot(self, other, **kw)

    def sigmoid(self):
        from . import _gen
        return _gen.sigmoid(self)

    def tanh(self):
        from . import _gen
        return _gen.tanh(self)

    def relu(self):
        from . import _gen
        return _gen.relu(self)

    def softmax(self, axis=-1):
        from . import _gen
        return _gen.softmax(self, axis=axis)

    def log_softmax(self, axis=-1):
        from . import _gen
        return _gen.log_softmax(self, axis=axis)

    def slice_axis(self, axis, begin, end):
        from . import _gen
        return _gen.slice_axis(self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        from . import _gen
        return _gen.take(self, indices, axis=axis, mode=mode)

    def one_hot(self, depth, **kw):
        from . import _gen
        return _gen.one_hot(self, depth=depth, **kw)

    def swapaxes(self, dim1, dim2):
        from . import _gen
        return _gen.swapaxes(self, dim1=dim1, dim2=dim2)

    def flip(self, axis):
        from . import _gen
        return _gen.flip(self, axis=axis)

    def tile(self, reps):
        from . import _gen
        return _gen.tile(self, reps=reps)

    def repeat(self, repeats, axis=None):
        from . import _gen
        return _gen.repeat(self, repeats=repeats, axis=axis)

    def pad(self, mode, pad_width, constant_value=0.0):
        from . import _gen
        return _gen.pad(self, mode=mode, pad_width=pad_width,
                        constant_value=constant_value)

    def topk(self, **kw):
        from . import _gen
        return _gen.topk(self, **kw)

    def sort(self, **kw):
        from . import _gen
        return _gen.sort(self, **kw)

    def argsort(self, **kw):
        from . import _gen
        return _gen.argsort(self, **kw)

    def round(self):
        from . import _gen
        return _gen.round(self)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        """Run autograd from this head (parity: ndarray.py backward)."""
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    # pickling (used by Updater.get_states / multiprocessing DataLoader)
    def __reduce__(self):
        return (_rebuild_ndarray, (self.asnumpy(), self._ctx.device_type,
                                   self._ctx.device_id))


def _rebuild_ndarray(np_data, dev_type, dev_id):
    return array(np_data, ctx=Context(dev_type, dev_id), dtype=np_data.dtype)


# ---------------------------------------------------------------------------
# creation helpers (parity: python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------
def _place(jarr, ctx: Optional[Context]) -> NDArray:
    ctx = ctx or current_context()
    return NDArray(jax.device_put(jarr, ctx.jax_device()), ctx)


def array(source_array, ctx=None, dtype=None, **kw) -> NDArray:
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = _np.asarray(source_array)
    if dtype is None:
        # parity: mx.nd.array keeps numpy/NDArray dtype, defaults python
        # lists/scalars to float32 (python/mxnet/ndarray/utils.py)
        dtype = src.dtype if isinstance(source_array, (_np.ndarray, NDArray)) \
            else _np.float32
    return _place(jnp.asarray(src.astype(np_dtype(dtype))), ctx)


def zeros(shape, ctx=None, dtype=None, stype=None, **kw) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.zeros(shape, np_dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kw) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.ones(shape, np_dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kw) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.full(shape, val, np_dtype(dtype)), ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None,
           **kw) -> NDArray:
    out = jnp.arange(start, stop, step, np_dtype(dtype or "float32"))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return _place(out, ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    return _place(jnp.eye(N, M or N, k=k, dtype=np_dtype(dtype)), ctx)


def from_numpy(a, zero_copy=False) -> NDArray:
    return array(a)


def from_dlpack(cap) -> NDArray:
    return NDArray(jnp.from_dlpack(cap))


def _mod_fn(dunder, mirror):
    """Module-level binary helper (parity: the ndarray.py free functions
    equal/greater/... that mirror the operator dunders).  A scalar lhs
    dispatches the MIRRORED comparison on the NDArray rhs
    (greater(2, x) == x < 2)."""
    def fn(lhs, rhs):
        if isinstance(lhs, NDArray):
            return getattr(lhs, dunder)(rhs)
        if isinstance(rhs, NDArray):
            return getattr(rhs, mirror)(lhs)
        raise TypeError("at least one operand must be an NDArray")
    return fn


equal = _mod_fn("__eq__", "__eq__")
not_equal = _mod_fn("__ne__", "__ne__")
greater = _mod_fn("__gt__", "__lt__")
greater_equal = _mod_fn("__ge__", "__le__")
lesser = _mod_fn("__lt__", "__gt__")
lesser_equal = _mod_fn("__le__", "__ge__")
modulo = _mod_fn("__mod__", "__rmod__")
true_divide = _mod_fn("__truediv__", "__rtruediv__")


def onehot_encode(indices, out):
    """Deprecated one-hot (parity: ndarray.onehot_encode — kept for v0
    compat; use `one_hot`)."""
    from . import _gen
    return _gen.one_hot(indices, depth=out.shape[1], out=out)


def moveaxis(a: NDArray, source, destination) -> NDArray:
    return NDArray(jnp.moveaxis(a._data, source, destination), a._ctx)


def concatenate(arrays: Sequence[NDArray], axis=0, always_copy=True) -> NDArray:
    return NDArray(jnp.concatenate([a._data for a in arrays], axis=axis),
                   arrays[0]._ctx)


def waitall() -> None:
    _engine.wait_for_all()


# ---------------------------------------------------------------------------
# save / load (parity API: mx.nd.save/load — src/c_api/c_api.cc:307,330)
# ---------------------------------------------------------------------------
def save(fname: str, data) -> None:
    """Save NDArray / list / dict of NDArrays to one file (.npz container)."""
    if isinstance(data, NDArray):
        payload = {"__mx_single__": data.asnumpy()}
    elif isinstance(data, dict):
        payload = {k: v.asnumpy() for k, v in data.items()}
    elif isinstance(data, (list, tuple)):
        payload = {f"__mx_list_{i:06d}": v.asnumpy() for i, v in enumerate(data)}
    else:
        raise MXNetError("save expects NDArray, list, or dict")
    import os
    # write to a temp file in the same directory, then one atomic
    # os.replace: a crash mid-save must never corrupt an existing file
    # at `fname` (model.save_checkpoint overwrites .params in place)
    tmp = f"{fname}.tmp-{os.getpid()}"
    _np.savez(tmp, **payload)  # numpy appends .npz when missing
    os.replace(tmp + ".npz", fname)


def _from_npz(z):
    keys = list(z.keys())
    if keys == ["__mx_single__"]:
        return array(z["__mx_single__"])
    if all(k.startswith("__mx_list_") for k in keys):
        return [array(z[k]) for k in sorted(keys)]
    return {k: array(z[k]) for k in keys}


def load(fname: str):
    # reference-era binary .params files (dmlc list container) load
    # transparently — load_checkpoint on a reference checkpoint works
    from ..legacy_format import is_reference_format, load_reference_format
    if is_reference_format(fname):
        return load_reference_format(fname)
    with _np.load(fname, allow_pickle=False) as z:
        return _from_npz(z)


def load_frombuffer(buf):
    """Deserialize an in-memory param/array blob — what `load` does for
    a file, without the file (parity: MXNDArrayLoadFromBuffer,
    c_api.cc; the C predict API hands the param blob over by pointer).
    Accepts both container formats `load` does: reference-era dmlc list
    files and the .npz container `save` writes."""
    import io as _io
    from ..legacy_format import (is_reference_buffer,
                                 load_reference_buffer)
    buf = bytes(buf)
    if is_reference_buffer(buf):
        return load_reference_buffer(buf)
    with _np.load(_io.BytesIO(buf), allow_pickle=False) as z:
        return _from_npz(z)
