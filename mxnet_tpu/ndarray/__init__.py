"""`mx.nd` namespace: NDArray + one function per registered operator.

Parity: `python/mxnet/ndarray/__init__.py` — flat op functions plus
`random`, `linalg`, `sparse` sub-namespaces.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange, eye,
                      concatenate, moveaxis, waitall, save, load, from_numpy,
                      from_dlpack)
from . import register
from .register import invoke, _gen

# hoist every generated op function into this namespace: mx.nd.<op>(...)
_g = globals()
for _name in dir(_gen):
    if not _name.startswith("__"):
        _g[_name] = getattr(_gen, _name)

from . import random
from . import linalg
from . import sparse
from .sparse import CSRNDArray, RowSparseNDArray

# storage-class-aware forms shadow the value-level generated ops
cast_storage = sparse.cast_storage
sparse_retain = sparse.retain

onehot_encode = _gen.one_hot
imdecode = None  # provided by mxnet_tpu.image


def maximum(lhs, rhs, **kw):
    """Elementwise max of arrays/scalars (parity: nd.maximum)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _gen.broadcast_maximum(lhs, rhs)
    if isinstance(lhs, NDArray):
        return _gen._maximum_scalar(lhs, scalar=float(rhs))
    return _gen._maximum_scalar(rhs, scalar=float(lhs))


def minimum(lhs, rhs, **kw):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _gen.broadcast_minimum(lhs, rhs)
    if isinstance(lhs, NDArray):
        return _gen._minimum_scalar(lhs, scalar=float(rhs))
    return _gen._minimum_scalar(rhs, scalar=float(lhs))


def add(l, r):
    return l + r


def subtract(l, r):
    return l - r


def multiply(l, r):
    return l * r


def divide(l, r):
    return l / r


def power(l, r):
    return l ** r
