"""`mx.nd` namespace: NDArray + one function per registered operator.

Parity: `python/mxnet/ndarray/__init__.py` — flat op functions plus
`random`, `linalg`, `sparse` sub-namespaces.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange, eye,
                      concatenate, moveaxis, waitall, save, load, from_numpy,
                      from_dlpack)
from . import register
from .register import invoke, _gen

# hoist every generated op function into this namespace: mx.nd.<op>(...)
_g = globals()
for _name in dir(_gen):
    if not _name.startswith("__"):
        _g[_name] = getattr(_gen, _name)

from . import random
from . import linalg
from . import sparse
from .sparse import CSRNDArray, RowSparseNDArray

onehot_encode = _gen.one_hot
imdecode = None  # provided by mxnet_tpu.image
