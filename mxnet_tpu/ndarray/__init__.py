"""`mx.nd` namespace: NDArray + one function per registered operator.

Parity: `python/mxnet/ndarray/__init__.py` — flat op functions plus
`random`, `linalg`, `sparse` sub-namespaces.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange, eye,
                      concatenate, moveaxis, waitall, save, load,
                      load_frombuffer, from_numpy,
                      from_dlpack, equal, not_equal, greater, greater_equal,
                      lesser, lesser_equal, modulo, true_divide,
                      onehot_encode)
from ..legacy_format import save_reference_format, load_reference_format
from . import register
from .register import invoke, _gen

# hoist every generated op function into this namespace: mx.nd.<op>(...)
_g = globals()
for _name in dir(_gen):
    if not _name.startswith("__"):
        _g[_name] = getattr(_gen, _name)

from . import random
from . import linalg
from . import sparse
from .sparse import CSRNDArray, RowSparseNDArray

# storage-class-aware forms shadow the value-level generated ops
cast_storage = sparse.cast_storage
sparse_retain = sparse.retain

imdecode = None  # provided by mxnet_tpu.image


def maximum(lhs, rhs, **kw):
    """Elementwise max of arrays/scalars (parity: nd.maximum)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _gen.broadcast_maximum(lhs, rhs)
    if isinstance(lhs, NDArray):
        return _gen._maximum_scalar(lhs, scalar=float(rhs))
    if isinstance(rhs, NDArray):
        return _gen._maximum_scalar(rhs, scalar=float(lhs))
    return lhs if lhs > rhs else rhs


def minimum(lhs, rhs, **kw):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _gen.broadcast_minimum(lhs, rhs)
    if isinstance(lhs, NDArray):
        return _gen._minimum_scalar(lhs, scalar=float(rhs))
    if isinstance(rhs, NDArray):
        return _gen._minimum_scalar(rhs, scalar=float(lhs))
    return lhs if lhs < rhs else rhs


def hypot(lhs, rhs):
    """sqrt(lhs² + rhs²) of arrays/scalars (parity: nd.hypot)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _gen.broadcast_hypot(lhs, rhs)
    if isinstance(lhs, NDArray):
        return _gen._hypot_scalar(lhs, scalar=float(rhs))
    if isinstance(rhs, NDArray):
        return _gen._hypot_scalar(rhs, scalar=float(lhs))
    return (lhs * lhs + rhs * rhs) ** 0.5


def add(l, r):
    return l + r


def subtract(l, r):
    return l - r


def multiply(l, r):
    return l * r


def divide(l, r):
    return l / r


def power(l, r):
    return l ** r


pow = power
