"""KVStore: the distributed key-value parameter store.

Reference parity: `include/mxnet/kvstore.h:47`, `src/kvstore/` (local comm
tree-reduce, NCCL collectives, ps-lite dist_sync/dist_async — SURVEY.md §2.3)
and `python/mxnet/kvstore.py`.

TPU-native design (SURVEY.md §2.3 "TPU-native equivalent"):
  - 'local' / 'device': in-process aggregation across per-device copies —
    push reduces (sum) the listed values, pull broadcasts; XLA executes the
    reduce as one fused kernel.  (replaces CommCPU/CommDevice, comm.h:102,484)
  - 'tpu_sync' (also accepted: 'nccl', 'dist_sync', 'dist_device_sync'):
    synchronous data parallelism over the ICI mesh.  Within one process,
    device-parallel gradients are averaged by XLA all-reduce (jnp sum over
    stacked device shards → compiler collective); across processes
    (multi-host pods), push/pull lower to `jax.lax.psum` inside a
    `shard_map` over the global mesh — see `mxnet_tpu.parallel`.  rank =
    jax.process_index(), num_workers = jax.process_count().
  - 'dist_async' has no ICI analog (parameter-server asynchrony); it is
    accepted and runs synchronously (documented divergence).
  - gradient compression: the reference's 2-bit stochastic quantization
    with error feedback (`src/kvstore/gradient_compression.h:37-134`) is
    implemented here as jit-compiled XLA ops (quantize/pack into uint8,
    4 codes/byte; per-key residual carries the quantization error forward).
    On ICI it is off by default (bandwidth makes it unnecessary); when
    enabled via `set_gradient_compression` it is applied on the push path —
    the useful case is DCN-connected multi-slice training.  The fused
    Trainer path composes it with bucketed allreduce:
    `allreduce(values, compression=..., residuals=...)` quantizes flat
    gradient buckets against flat residuals in one program and ships only
    the packed payload on the dist leg (worker-quantize /
    dequantize-sum split, parity: kvstore_dist.h PushCompressed).
"""
from __future__ import annotations

import functools
import pickle
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from .analysis import hot_path
from .base import MXNetError, atomic_write, getenv
from .faultinject import fire as _fi_fire
from . import ndarray as nd
from .ndarray import NDArray
from .observability import memory as _memory
from .observability import metrics as _metrics
from .observability.tracing import trace_span
from . import optimizer as opt


def _nd_bytes(v) -> int:
    """Byte size of an NDArray / sparse NDArray / raw jax array.  Sparse
    is checked FIRST: RowSparseNDArray._data is a densifying property, so
    going through it would dispatch an O(N) scatter-add per accounted
    value and report dense bytes instead of nnz bytes."""
    iv = getattr(v, "_values", None)
    if iv is not None:
        ii = getattr(v, "_indices", None)
        return int((getattr(iv, "nbytes", 0) or 0)
                   + (getattr(ii, "nbytes", 0) or 0))
    d = getattr(v, "_data", v)
    return int(getattr(d, "nbytes", 0) or 0)


def _handoff(src: NDArray, dst: NDArray) -> None:
    """Pull a store value into `dst`.  Arrays are immutable jax values, so
    when dtype and placement already match this is a pointer hand-off —
    zero device operations — instead of the reference's engine CopyTo.
    Per-key device_puts here were the Module.update bottleneck on the
    tunneled TPU (one RPC per parameter per step)."""
    from .ndarray.sparse import RowSparseNDArray
    if isinstance(dst, RowSparseNDArray):
        if isinstance(src, RowSparseNDArray):
            dst._assign_rows(src._indices, src._values)
        else:
            from .ndarray.sparse import row_sparse_array
            rs = row_sparse_array(src)
            dst._assign_rows(rs._indices, rs._values)
        return
    sd, dd = src._data, dst._data
    if (sd.dtype == dd.dtype and
            getattr(sd, "sharding", None) == getattr(dd, "sharding", None)):
        dst._set_data(sd)
    else:
        src.copyto(dst)


def _quantize_2bit_impl(arr, residual, threshold):
    """2-bit quantization with error feedback (pure; traceable inside any
    outer jit — the fused pushpull path inlines it).

    Parity: GradientCompression::Quantize2Bit
    (`src/kvstore/gradient_compression.h:111`, kernel in
    gradient_compression-inl.h): r = grad + residual; elements >= +T map to
    +T (code 1), <= -T map to -T (code 2), else 0 (code 0); the residual
    keeps r - quantized so the error feeds the next step.  Codes are packed
    four-per-byte (the reference packs 16 per float32 — same 2 bits/elt).
    """
    r = arr.astype(jnp.float32) + residual
    pos = r >= threshold
    neg = r <= -threshold
    out = jnp.where(pos, threshold, jnp.where(neg, -threshold, 0.0))
    new_residual = r - out
    codes = jnp.where(pos, 1, jnp.where(neg, 2, 0)).astype(jnp.uint8).ravel()
    n = codes.shape[0]
    pad = (-n) % 4
    codes = jnp.pad(codes, (0, pad)).reshape(-1, 4)
    packed = (codes[:, 0] | (codes[:, 1] << 2) | (codes[:, 2] << 4)
              | (codes[:, 3] << 6))
    return packed, new_residual


def _dequantize_2bit_impl(packed, threshold, size):
    """Parity: GradientCompression::Dequantize2Bit (pure; traceable)."""
    codes = jnp.stack([packed & 3, (packed >> 2) & 3, (packed >> 4) & 3,
                       (packed >> 6) & 3], axis=1).ravel()[:size]
    return jnp.where(codes == 1, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))


_quantize_2bit = jax.jit(_quantize_2bit_impl,
                         static_argnames=("threshold",))
_dequantize_2bit = jax.jit(_dequantize_2bit_impl,
                           static_argnames=("threshold", "size"))


# -- bucket-level compressed allreduce programs -------------------------------
# The quantizer is purely elementwise, so running it over FLAT GRADIENT
# BUCKETS (kvstore.GradBucketer) with flat residual buffers preserves the
# reference's per-parameter error-feedback semantics exactly — each
# parameter's residual occupies its own slice of the bucket residual.
# That is what lets 2-bit compression compose with the O(1)-dispatch fused
# Trainer path instead of forcing the O(num_params) per-key loop.
# jax.jit keys these module-level programs on bucket shapes + threshold,
# so a signature change re-selects a cached program rather than retracing
# under the same entry (same dispatch-stability rule as FusedUpdater).

def _quantize_buckets_impl(flats, residuals, threshold):
    """Per-bucket quantize with the residual update fused into the SAME
    program (worker-side half of kvstore_dist.h PushCompressed) — one
    launch for every bucket.  Also emits each bucket's mean |error| (=
    mean |new residual|) so the compression_error histogram costs no
    extra program."""
    packeds, new_res, errs = [], [], []
    for f, r in zip(flats, residuals):
        packed, nr = _quantize_2bit_impl(f.reshape(-1), r, threshold)
        packeds.append(packed)
        new_res.append(nr)
        errs.append(jnp.mean(jnp.abs(nr)))
    return packeds, new_res, errs


def _dequantize_sum_impl(stacks, threshold, shapes, dtypes):
    """Dequantize every worker's packed payload and sum — the
    server-side half of the reference split (kvstore_dist_server.h
    DecompressAndMerge), one launch for every bucket.  stacks[k] is
    (num_workers, packed_len) uint8."""
    outs = []
    for st, shape, dt in zip(stacks, shapes, dtypes):
        size = 1
        for s in shape:
            size *= s
        vals = jax.vmap(
            lambda p, _t=threshold, _n=size: _dequantize_2bit_impl(
                p, _t, _n))(st)
        outs.append(jnp.sum(vals, axis=0).reshape(shape).astype(dt))
    return outs


def _compressed_reduce_local_impl(flats, residuals, threshold):
    """Single-process compressed reduce: quantize + residual update +
    dequantize fused into ONE program (there is no wire to cross, but
    the quantize→dequantize round trip must still run so training sees
    the same error-feedback trajectory as a multi-host pod — and as the
    reference's per-key path)."""
    packeds, new_res, errs = _quantize_buckets_impl(flats, residuals,
                                                    threshold)
    outs = [_dequantize_2bit_impl(p, threshold, f.size)
            .reshape(f.shape).astype(f.dtype)
            for p, f in zip(packeds, flats)]
    return outs, new_res, errs


# single-process: residuals (argnum 1) are donated — one fused program,
# the caller always replaces its copy with the returned one, so the old
# grad-sized f32 buffers back the new values in place.  The multi-host
# _quantize_buckets deliberately does NOT donate: the all-gather wire
# leg runs between quantize and the caller's reassignment, and a
# transient DCN failure there must leave the caller's residuals valid
# for retry, not pointing at deleted buffers.
_quantize_buckets = jax.jit(_quantize_buckets_impl,
                            static_argnames=("threshold",))
_compressed_reduce_local = jax.jit(_compressed_reduce_local_impl,
                                   static_argnames=("threshold",),
                                   donate_argnums=(1,))
_dequantize_sum = jax.jit(_dequantize_sum_impl,
                          static_argnames=("threshold", "shapes", "dtypes"))


def reduce_buckets_inline(flats, residuals, threshold):
    """Pure single-process compressed bucket reduce for tracing INSIDE an
    outer jit: quantize + residual update + dequantize, no metrics, no
    NDArray wrapping, no dispatch of its own.  The gluon whole-step
    compiler (`gluon/wholestep.py`) inlines this into its one-program
    training step so 2-bit error feedback composes with whole-step
    compilation at zero extra launches; the math (and therefore the
    residual trajectory) is identical to the fused path's
    `_compressed_reduce_local` program.  Returns (reduced flats, new
    residuals, per-bucket mean |error|)."""
    return _compressed_reduce_local_impl(flats, residuals, threshold)


def reduce_rowsparse_inline(ids_parts, rows_parts, size=None, dedup=True,
                            fill=None):
    """Pure row-sparse gradient reduce (ISSUE 20): unique-concat +
    segment-sum over gathered (ids, rows) pairs, traceable INSIDE an
    outer jit exactly like ``reduce_buckets_inline`` — no metrics, no
    NDArray wrapping, no dispatch of its own.  The gluon whole-step
    compiler inlines this math into its donated one-program step; the
    eager ``KVStore.allreduce_rowsparse`` wrapper runs the same ops so
    the two trajectories stay bitwise-interchangeable.

    ``ids_parts``: int id vectors (one per gathered shard/copy);
    ``rows_parts``: the matching ``(n_i, ...)`` row blocks.  Returns
    ``(ids, rows)`` with ids sorted-unique and rows segment-summed
    (``zeros.at[inverse].add`` — the same op ``RowSparseNDArray``'s
    dedup uses, so already-unique input round-trips bitwise).

    ``size``: static output length for jit tracing (pad tail ids with
    ``fill``, default ``iinfo(ids.dtype).max`` — positively out of
    range for every table, so a downstream ``.at[ids].set/add(...,
    mode="drop")`` scatter ignores the padding; NEVER a negative fill,
    which python indexing would wrap onto real rows).  ``size=None``
    returns the exact nnz (eager use only — data-dependent shape).

    ``dedup=False`` (the ``MXNET_EMBED_DEDUP_IDS=0`` wire format) skips
    the unique pass and returns the raw concatenation — token-duplicate
    ids stay on the wire and the consumer (the fused sparse updater /
    whole-step scatter leg) performs the segment-sum itself."""
    ids = jnp.concatenate([jnp.ravel(i) for i in ids_parts])
    rows = jnp.concatenate(list(rows_parts))
    if not dedup:
        return ids, rows
    if fill is None:
        fill = jnp.iinfo(ids.dtype).max
    if size is None:
        uids, inv = jnp.unique(ids, return_inverse=True)
        n = int(uids.shape[0])
    else:
        n = int(size)
        uids, inv = jnp.unique(ids, size=n, fill_value=fill,
                               return_inverse=True)
    summed = jnp.zeros((n,) + rows.shape[1:], rows.dtype) \
        .at[jnp.ravel(inv)].add(rows)
    return uids, summed


class GradientCompression:
    """Parity: `src/kvstore/gradient_compression.h:37` — holds type +
    threshold; quantize/dequantize as XLA-compiled kernels."""

    def __init__(self, type="2bit", threshold=0.5):
        if type != "2bit":
            raise MXNetError("Unknown type for gradient compression " + type)
        if threshold <= 0:
            raise MXNetError("threshold must be greater than 0")
        self.type = type
        self.threshold = float(threshold)

    def quantize(self, grad: NDArray, residual):
        """Returns (packed uint8 NDArray — 4 elements/byte, new residual)."""
        packed, new_res = _quantize_2bit(grad.handle, residual,
                                         self.threshold)
        return NDArray(packed, grad.context), new_res

    def dequantize(self, packed: NDArray, shape) -> NDArray:
        size = 1
        for s in shape:
            size *= s
        vals = _dequantize_2bit(packed.handle, self.threshold, size)
        return NDArray(vals.reshape(shape), packed.context)

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}


class GradBucketer:
    """Size-capped dense-gradient bucketing for O(1)-dispatch allreduce.

    The reference allreduces one engine push per key (kvstore_local.h); here
    all dense grads are grouped into dtype-homogeneous, order-preserving
    buckets of at most `cap_bytes` (MXNET_BUCKET_SIZE_MB) and each bucket
    crosses the kvstore as ONE flat array — pushes per step become
    O(total grad bytes / cap), independent of parameter count.

    `flatten` runs as a single jitted program over every bucket.  `views`
    maps each input position to (bucket, offset, shape) so
    `FusedUpdater.update_all(grad_views=...)` slices gradients straight out
    of the reduced flat buckets inside its own fused program (un-flattening
    is free on the trainer hot path); `unflatten` materializes per-key
    grads only for the public `Trainer.allreduce_grads()` contract.
    """

    def __init__(self, sig, cap_bytes: int):
        # sig: tuple of (shape, dtype_str) in input order
        self.sig = tuple((tuple(s), str(d)) for s, d in sig)
        self.cap = max(1, int(cap_bytes))
        layout: List[tuple] = []
        cur: List[int] = []
        cur_dtype, cur_bytes = None, 0
        for pos, (shape, dtype) in enumerate(self.sig):
            nbytes = int(_np.dtype(dtype).itemsize * _np.prod(shape)) \
                if shape else _np.dtype(dtype).itemsize
            if cur and (dtype != cur_dtype or cur_bytes + nbytes > self.cap):
                layout.append(tuple(cur))
                cur, cur_bytes = [], 0
            cur.append(pos)
            cur_dtype, cur_bytes = dtype, cur_bytes + nbytes
        if cur:
            layout.append(tuple(cur))
        self.layout = tuple(layout)
        self.views: List[tuple] = [None] * len(self.sig)
        sizes: List[int] = []
        for b, bucket in enumerate(self.layout):
            off = 0
            for pos in bucket:
                shape, _ = self.sig[pos]
                size = int(_np.prod(shape)) if shape else 1
                self.views[pos] = (b, off, shape)
                off += size
            sizes.append(off)
        # total elements per flat bucket — the Trainer sizes its
        # error-feedback residual buffers off this
        self.sizes = tuple(sizes)
        lay, sig_ = self.layout, self.sig

        def _flat(gs):
            return [jnp.concatenate([gs[p].reshape(-1) for p in bucket])
                    if len(bucket) > 1 else gs[bucket[0]].reshape(-1)
                    for bucket in lay]

        def _unflat(flats):
            out = [None] * len(sig_)
            for b, bucket in enumerate(lay):
                off = 0
                for p in bucket:
                    shape = sig_[p][0]
                    size = int(_np.prod(shape)) if shape else 1
                    out[p] = flats[b][off:off + size].reshape(shape)
                    off += size
            return out

        # pure, jit-inlinable forms (no metrics, no dispatch of their
        # own): the whole-step compiler traces these inside its single
        # training-step program instead of issuing the jitted wrappers
        self.flatten_inline = _flat
        self.unflatten_inline = _unflat
        self._flatten = jax.jit(_flat)
        self._unflatten = jax.jit(_unflat)

    @hot_path
    def flatten(self, grads: List) -> List:
        """Raw jax arrays in sig order -> flat bucket arrays (one dispatch)."""
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="allreduce")
            _metrics.ALLREDUCE_BUCKETS.set(len(self.layout))
        return self._flatten(grads)

    @hot_path
    def unflatten(self, flats: List) -> List:
        """Flat bucket arrays -> per-key arrays (one dispatch)."""
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="allreduce")
        return self._unflatten(flats)


def _key_list(key):
    if isinstance(key, (int, str)):
        return [key], False
    return list(key), True


def _val_list(value):
    if isinstance(value, NDArray):
        return [[value]]
    if isinstance(value, (list, tuple)):
        if value and isinstance(value[0], NDArray):
            return [list(value)]
        return [list(v) if isinstance(v, (list, tuple)) else [v] for v in value]
    raise MXNetError("invalid kvstore value")


class KVStore:
    def __init__(self, kv_type: str = "local"):
        self.type = kv_type
        self._store: Dict = {}
        self._updater = None
        self._update_on_kvstore = True
        self._compression_params = None
        self._gc: Optional[GradientCompression] = None
        self._residuals: Dict = {}
        self._merge_cache: Dict = {}
        self._optimizer = None

    # -- identity -----------------------------------------------------------
    @property
    def rank(self) -> int:
        return jax.process_index() if self.type.startswith(("dist", "tpu")) else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self.type.startswith(("dist", "tpu")) else 1

    # -- core ops -----------------------------------------------------------
    def init(self, key, value) -> None:
        keys, _ = _key_list(key)
        vals = _val_list(value)
        # HBM ledger: the backing store pins one device copy per key —
        # a full model's worth of HBM that the bucketed fast path never
        # touches; attributing it is exactly what makes that cost
        # visible in memory.report()
        with _memory.memory_scope("kvstore"):
            for k, vlist in zip(keys, vals):
                self._store[k] = vlist[0].copy()

    @staticmethod
    def _merge_local(vlist):
        """Reduce per-device copies of one key (parity: comm.h Reduce).
        All-rsp lists take the union-of-rows path — O(sum nnz) concat +
        dedup, never dense — so the updater stays on the lazy path."""
        from .ndarray.sparse import RowSparseNDArray
        if len(vlist) > 1 and all(isinstance(v, RowSparseNDArray)
                                  for v in vlist):
            return RowSparseNDArray(
                jnp.concatenate([v._indices for v in vlist]),
                jnp.concatenate([v._values for v in vlist]),
                vlist[0].shape, vlist[0].context)
        merged = vlist[0]
        for v in vlist[1:]:
            merged = merged + v
        return merged

    def _global_dense(self, k, merged):
        """Cross-host leg for one dense key: compress (dist only), then
        DCN all-reduce (parity: kvstore_dist.h PushCompressed)."""
        if self._gc is not None:
            merged = self._compress(k, merged)
        return self._allreduce(merged)

    def _apply_merged(self, k, merged) -> None:
        """Updater-or-assign for one key's globally-merged value."""
        if self._updater is not None:
            if k not in self._store:
                raise MXNetError(f"key {k} has not been inited")
            self._updater(_updater_key(k), merged, self._store[k])
        else:
            # parity: kvstore_local.h:191 — assign, not accumulate
            self._store[k] = merged.copy()

    def push(self, key, value, priority: int = 0) -> None:
        """Aggregate `value` (list = per-device copies) into the store.
        If an optimizer is set (update_on_kvstore), applies the update."""
        if _metrics.ENABLED:
            t0 = time.perf_counter()
            with trace_span("kvstore_push", cat="kvstore"):
                self._push_impl(key, value, priority)
            # success path only: a failed push must not count as pushed
            _metrics.KVSTORE_ALLREDUCE_SECONDS.observe(
                time.perf_counter() - t0)
            _metrics.KVSTORE_PUSH_BYTES.inc(sum(
                _nd_bytes(v) for vl in _val_list(value) for v in vl))
        else:
            self._push_impl(key, value, priority)

    def _push_impl(self, key, value, priority: int = 0) -> None:
        keys, _ = _key_list(key)
        vals = _val_list(value)
        from .ndarray.sparse import RowSparseNDArray
        for k, vlist in zip(keys, vals):
            merged = self._merge_local(vlist)
            if isinstance(merged, RowSparseNDArray):
                # rows-only cross-host union: ship rows+indices over DCN
                # (parity: kvstore_dist.h rsp push; compression applies
                # to dense grads only, as in the reference)
                if self.num_workers > 1 and self.type != "local":
                    from .parallel import collectives
                    ids, vls = collectives.allgather_rows(
                        merged._indices, merged._values)
                    merged = RowSparseNDArray(ids, vls, merged.shape,
                                              merged.context)
            else:
                merged = self._global_dense(k, merged)
            self._apply_merged(k, merged)

    def pushpull(self, key, value, out=None, priority: int = 0) -> None:
        """Fused push+pull over MANY keys in O(1) XLA dispatches.

        The TPU redesign of the reference's per-key engine pushes
        (`_update_params_on_kvstore`, model.py:126): device-copy merge +
        gradient compression trace into one jitted program, the optimizer
        applies to every key via FusedUpdater.update_all (one more program),
        and pull is a pointer hand-off.  Semantics are identical to
        push(key, value); pull(key, out) — verified by tests/test_kvstore.py.
        """
        if _metrics.ENABLED:
            t0 = time.perf_counter()
            with trace_span("kvstore_pushpull", cat="kvstore"):
                self._pushpull_impl(key, value, out, priority)
            # success path only: a failed pushpull must not count bytes
            _metrics.KVSTORE_ALLREDUCE_SECONDS.observe(
                time.perf_counter() - t0)
            _metrics.KVSTORE_PUSH_BYTES.inc(sum(
                _nd_bytes(v) for vl in _val_list(value) for v in vl))
            if out is not None:
                _metrics.KVSTORE_PULL_BYTES.inc(sum(
                    _nd_bytes(o) for ol in _val_list(out) for o in ol))
        else:
            self._pushpull_impl(key, value, out, priority)

    def _pushpull_impl(self, key, value, out=None, priority: int = 0) -> None:
        keys, _ = _key_list(key)
        vals = _val_list(value)
        for k in keys:
            if k not in self._store:
                raise MXNetError(f"key {k} has not been inited")
        from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
        if any(isinstance(v, BaseSparseNDArray) for vl in vals for v in vl):
            # sparse values keep their storage class (row-sparse lazy
            # updates; parity: kvstore_local.h rsp).  The cross-host
            # union for ALL rsp keys is batched into one two-program
            # collective per step (VERDICT r3 #4) — dense keys and the
            # updater stay per-key.
            outs = _val_list(out) if out is not None else [None] * len(keys)
            # one local merge per key OCCURRENCE (repeated keys apply
            # each occurrence's gradient, like the per-key push path)
            merged_all = [self._merge_local(vl) for vl in vals]
            if self.num_workers > 1 and self.type != "local":
                rsp_pos = [i for i, m in enumerate(merged_all)
                           if isinstance(m, RowSparseNDArray)]
                if rsp_pos:
                    from .parallel import collectives
                    got = collectives.allgather_rows_many(
                        [(merged_all[i]._indices, merged_all[i]._values)
                         for i in rsp_pos])
                    for i, (ids, vls) in zip(rsp_pos, got):
                        m = merged_all[i]
                        merged_all[i] = RowSparseNDArray(
                            ids, vls, m.shape, m.context)
            for k, m, ol in zip(keys, merged_all, outs):
                if not isinstance(m, RowSparseNDArray):
                    m = self._global_dense(k, m)
                self._apply_merged(k, m)
                if ol is not None:
                    self.pull(k, out=ol)
            return
        if any(len(v) > 1 for v in vals) or self._gc is not None:
            merged = self._fused_merge(keys, vals)
        else:
            merged = [v[0]._data if isinstance(v[0], NDArray) else v[0]
                      for v in vals]
        if self.num_workers > 1 and self.type != "local":
            from .parallel import collectives
            merged = collectives.allreduce_hosts_many(merged)
        if self._updater is not None:
            if isinstance(self._updater, opt.FusedUpdater):
                self._updater.update_all([_updater_key(k) for k in keys],
                                         merged, [self._store[k] for k in keys])
            else:
                for k, m in zip(keys, merged):
                    m = m if isinstance(m, NDArray) else \
                        NDArray(m, self._store[k].context)
                    self._updater(_updater_key(k), m, self._store[k])
        else:
            for k, m in zip(keys, merged):
                m = m if isinstance(m, NDArray) else \
                    NDArray(m, self._store[k].context)
                self._store[k] = m.copy()
        if out is not None:
            outs = _val_list(out)
            for k, olist in zip(keys, outs):
                src = self._store[k]
                for o in olist:
                    if o is not src:
                        _handoff(src, o)

    def _fused_merge(self, keys, vals) -> List:
        """One jitted program: per-key device-copy sum (+2-bit compression
        with error-feedback residuals).  Returns raw jax arrays."""
        gc = self._gc
        thr = gc.threshold if gc is not None else 0.0
        vdata = [[v._data if isinstance(v, NDArray) else v for v in vl]
                 for vl in vals]
        res = []
        if gc is not None:
            for k, vl in zip(keys, vdata):
                r = self._residuals.get(k)
                if r is None:
                    r = jnp.zeros(vl[0].size, dtype=jnp.float32)
                res.append(r)
        fkey = ("merge", tuple(keys), tuple(len(v) for v in vals),
                thr, gc is not None)
        fn = self._merge_cache.get(fkey)
        if fn is None:
            use_gc = gc is not None

            def _m(vlists, residuals):
                outs, new_res = [], []
                for i, vl in enumerate(vlists):
                    m = vl[0]
                    for v in vl[1:]:
                        m = m + v
                    if use_gc:
                        packed, nr = _quantize_2bit_impl(
                            m.reshape(-1), residuals[i], thr)
                        m = _dequantize_2bit_impl(packed, thr, m.size) \
                            .reshape(m.shape).astype(m.dtype)
                        new_res.append(nr)
                    outs.append(m)
                return outs, new_res

            fn = jax.jit(_m, donate_argnums=(1,))
            self._merge_cache[fkey] = fn
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="kvstore_merge")
        merged, new_res = fn(vdata, res)
        if gc is not None:
            for k, nr in zip(keys, new_res):
                self._residuals[k] = nr
        return merged

    def pull(self, key, out=None, priority: int = 0) -> None:
        keys, _ = _key_list(key)
        outs = _val_list(out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            for o in olist:
                _handoff(src, o)
            if _metrics.ENABLED:
                _metrics.KVSTORE_PULL_BYTES.inc(
                    _nd_bytes(src) * len(olist))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None) -> None:
        """Pull only the rows in row_ids (parity: KVStore::PullRowSparse)."""
        keys, _ = _key_list(key)
        outs = _val_list(out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        from .ndarray.sparse import RowSparseNDArray, gather_rows
        for k, olist in zip(keys, outs):
            src = self._store[k]
            for o, rid in zip(olist, rids * len(olist)):
                idx = _np.unique(
                    rid.asnumpy().astype("int64").ravel())
                # device-side gather of just the requested rows —
                # no host round trip, no dense copy (parity:
                # kvstore_local.h PullRowSparse)
                rows = gather_rows(src, idx)
                if isinstance(o, RowSparseNDArray):
                    o._assign_rows(idx, rows)
                else:
                    o._set_data(jnp.zeros(src.shape, rows.dtype)
                                .at[jnp.asarray(idx)].set(rows))

    # -- allreduce across processes (multi-host pods) ------------------------
    def _allreduce(self, merged: NDArray) -> NDArray:
        if self.num_workers <= 1 or self.type == "local":
            return merged
        from .parallel import collectives
        with trace_span("kvstore_allreduce", cat="kvstore"):
            return collectives.allreduce_hosts(merged)

    @hot_path
    def allreduce(self, values: List[NDArray], compression=None,
                  residuals=None):
        """Store-less dense allreduce: sum each value across its per-device
        copies and across hosts, return the reduced arrays.

        For TRANSIENT keys (the Trainer's gradient buckets) — unlike
        push/pull nothing is `init`ed or persisted, so reducing N bytes
        costs no store copy and pins no store memory.  `values` is a list
        with one entry PER VALUE: an NDArray, or that value's
        per-device-copy list of NDArrays.  (Unlike push/pushpull, a flat
        NDArray list here means N distinct values — never N device
        copies of one value.)

        compression: a GradientCompression (or compression_params dict)
        switches on the 2-bit error-feedback leg and changes the return
        to ``(reduced, new_residuals)``.  The intra-host device-copy
        merge stays FULL precision (parity: the reference compresses
        only the worker→server leg, kvstore_dist.h PushCompressed);
        each value is then quantized against its entry in `residuals`
        (flat f32 arrays OWNED BY THE CALLER, zero-initialized here when
        None — note the old arrays are donated to XLA, so the caller
        must replace its copy with the returned ones) and only the
        PACKED payload (4 codes/byte) crosses the dist leg, which
        all-gathers the packed buckets and dequantize-sums them.  On a
        single process the quantize→dequantize round trip still runs —
        same training trajectory as a pod, and as the reference's
        per-key path — fused into one program."""
        vals = [list(v) if isinstance(v, (list, tuple)) else [v]
                for v in values]
        # chaos site: a raise here models a failed gradient collective
        # (dropped pod peer, tunnel loss).  Fires BEFORE any reduce
        # work, so residuals/buckets are untouched and the supervisor's
        # snapshot retry re-executes the step cleanly.  (Whole-step mode
        # inlines the reduce into the donated program — this site only
        # fires on the fused/legacy paths.)
        _fi_fire("kvstore.allreduce", values=len(vals))
        if compression is not None and not isinstance(
                compression, GradientCompression):
            compression = GradientCompression(**compression)
        if _metrics.ENABLED:
            t0 = time.perf_counter()
            with trace_span("kvstore_allreduce", cat="kvstore"):
                out = self._allreduce_impl(vals) if compression is None \
                    else self._compressed_allreduce_impl(
                        vals, residuals, compression)
            _metrics.KVSTORE_ALLREDUCE_SECONDS.observe(
                time.perf_counter() - t0)
            _metrics.KVSTORE_PUSH_BYTES.inc(sum(
                _nd_bytes(v) for vl in vals for v in vl))
            return out
        if compression is not None:
            return self._compressed_allreduce_impl(vals, residuals,
                                                   compression)
        return self._allreduce_impl(vals)

    def _allreduce_impl(self, vals: List[List[NDArray]]) -> List[NDArray]:
        merged = [self._merge_local(vl) for vl in vals]
        raw = [m._data if isinstance(m, NDArray) else m for m in merged]
        if self.num_workers > 1 and self.type != "local":
            from .parallel import collectives
            raw = collectives.allreduce_hosts_many(raw)
        return [r if isinstance(r, NDArray) else NDArray(r, vl[0].context)
                for r, vl in zip(raw, vals)]

    @hot_path
    def allreduce_rowsparse(self, values):
        """Store-less ROW-SPARSE allreduce (ISSUE 20): the sparse twin of
        ``allreduce`` — each value's per-device (ids, rows) pairs reduce
        by unique-concat + segment-sum (``reduce_rowsparse_inline``),
        never densifying the O(vocab) gradient.  For TRANSIENT keys (the
        Trainer's row-sparse embedding grads): nothing is init'ed or
        persisted, so reducing nnz rows costs nnz — not vocab — bytes.

        ``values``: one entry per VALUE — a RowSparseNDArray or that
        value's per-device-copy list.  Returns the reduced
        RowSparseNDArrays (sorted-unique ids, summed rows).

        ``MXNET_EMBED_DEDUP_IDS=0`` keeps token-duplicate ids on the
        wire (the unique pass is skipped here; the fused sparse updater
        segment-sums at the scatter instead) — the knob trades wire rows
        for one fused dedup, and both settings train bitwise-identically
        because the segment-sum runs exactly once either way."""
        from .ndarray import sparse as _sp
        vals = [list(v) if isinstance(v, (list, tuple)) else [v]
                for v in values]
        # chaos site: a raise here models a failed SPARSE gradient
        # collective.  Fires BEFORE any reduce work, so grads and
        # per-row optimizer state are untouched and the supervisor's
        # snapshot retry replays the step bitwise.  (Whole-step mode
        # inlines the sparse reduce into the donated program — this
        # site only fires on the fused/legacy paths.)
        _fi_fire("kvstore.sparse_allreduce", values=len(vals))
        for vl in vals:
            for v in vl:
                if not isinstance(v, _sp.RowSparseNDArray):
                    raise MXNetError(
                        "allreduce_rowsparse expects row_sparse values, "
                        f"got {type(v).__name__}")
        if self.num_workers > 1 and self.type != "local":
            raise MXNetError(
                "multi-host row-sparse allreduce is not wired yet — "
                "cast the gradient to dense storage or train this "
                "parameter single-host (documented in docs/embedding.md)")
        dedup = bool(getenv("MXNET_EMBED_DEDUP_IDS", True))
        t0 = time.perf_counter() if _metrics.ENABLED else 0.0
        out = []
        with trace_span("kvstore_sparse_allreduce", cat="kvstore"):
            for vl in vals:
                if len(vl) == 1 and dedup:
                    # construction guarantees sorted-unique ids — the
                    # single-copy reduce is the identity (rows-only, no
                    # segment-sum rerun: bitwise either way)
                    out.append(vl[0])
                    continue
                ids, rows = reduce_rowsparse_inline(
                    [v._indices for v in vl],
                    [v._values for v in vl], size=None, dedup=dedup)
                out.append(_sp.RowSparseNDArray(
                    ids, rows, shape=vl[0].shape, ctx=vl[0].context,
                    _dedup=not dedup))
        if _metrics.ENABLED:
            _metrics.KVSTORE_ALLREDUCE_SECONDS.observe(
                time.perf_counter() - t0)
            _metrics.KVSTORE_PUSH_BYTES.inc(sum(
                _nd_bytes(v) for vl in vals for v in vl))
        return out

    def _compressed_allreduce_impl(self, vals, residuals,
                                   gc: GradientCompression):
        """2-bit error-feedback allreduce over transient values (the
        Trainer's flat gradient buckets).  Returns (reduced NDArrays,
        new residuals).  Steady-state launches: 1 (fused quantize+
        dequantize+residual) on a single process; 3 (quantize, packed
        all-gather, dequantize-sum) on a multi-host pod — the wire
        moves ~1/16 of the float32 gradient bytes either way."""
        if not vals:
            return [], []
        merged = [self._merge_local(vl) for vl in vals]
        raw = [m._data if isinstance(m, NDArray) else m for m in merged]
        if residuals is None:
            residuals = [jnp.zeros(x.size, dtype=jnp.float32) for x in raw]
        thr = gc.threshold
        dist = self.num_workers > 1 and self.type != "local"
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="allreduce")
        if dist:
            packed, new_res, errs = _quantize_buckets(raw, residuals, thr)
            from .parallel import collectives
            stacks = collectives.allgather_stack_many(packed)
            if _metrics.ENABLED:
                _metrics.XLA_LAUNCHES.inc(2, kind="allreduce")
            out = _dequantize_sum(
                stacks, thr, tuple(tuple(x.shape) for x in raw),
                tuple(str(x.dtype) for x in raw))
        else:
            out, new_res, errs = _compressed_reduce_local(
                raw, residuals, thr)
        if _metrics.ENABLED:
            # wire accounting: dist stage=raw is what full precision
            # WOULD ship per worker; stage=compressed is the packed
            # payload that actually does (on a single process the dist
            # leg is virtual, but the payload math is exact — the CPU
            # acceptance gate reads these)
            _metrics.KVSTORE_WIRE_BYTES.set(
                sum(int(x.nbytes) for x in raw), leg="dist", stage="raw")
            _metrics.KVSTORE_WIRE_BYTES.set(
                sum((int(x.size) + 3) // 4 for x in raw),
                leg="dist", stage="compressed")
            _metrics.KVSTORE_WIRE_BYTES.set(
                sum(_nd_bytes(v) for vl in vals for v in vl),
                leg="intra", stage="raw")
            if getenv("MXNET_COMPRESSION_ERROR_METRIC", True):
                # float() blocks on the reduce program's tiny scalar
                # outputs; =0 skips the sync on latency-critical runs
                for e in errs:
                    _metrics.COMPRESSION_ERROR.observe(float(e))
        return ([o if isinstance(o, NDArray) else NDArray(o, vl[0].context)
                 for o, vl in zip(out, vals)], new_res)

    # -- optimizer plumbing --------------------------------------------------
    def set_optimizer(self, optimizer: "opt.Optimizer") -> None:
        """Run this optimizer on push (parity: server-side optimizer —
        kvstore_dist_server.h ApplyUpdates; here updates run worker-side,
        sharded by XLA, since there are no server processes on ICI)."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater) -> None:
        self._updater = updater

    def set_gradient_compression(self, compression_params: Dict) -> None:
        """Parity: python/mxnet/kvstore.py:363 set_gradient_compression —
        like the reference, only dist kvstores support compression (the
        worker→server leg is what it shrinks)."""
        if "type" not in compression_params:
            raise MXNetError("compression_params requires 'type'")
        if not ("device" in self.type or "dist" in self.type
                or self.type.startswith(("tpu", "nccl"))):
            # parity: kvstore.py set_gradient_compression — supported for
            # 'device' and 'dist' kvstores, rejected for CPU-local
            raise MXNetError(
                "gradient compression is not supported on kvstore type "
                f"'{self.type}' (supported: device/dist/tpu_sync/nccl)")
        try:
            self._gc = GradientCompression(**compression_params)
        except TypeError as e:
            raise MXNetError(f"invalid compression_params: {e}") from None
        self._compression_params = self._gc.get_params()
        self._residuals = {}

    def _compress(self, k, v: NDArray) -> NDArray:
        res = self._residuals.get(k)
        if res is None:
            res = jnp.zeros(v.size, dtype=jnp.float32)
        packed, new_res = self._gc.quantize(v.reshape((-1,)), res)
        self._residuals[k] = new_res
        return self._gc.dequantize(packed, v.shape)

    # -- cluster control ------------------------------------------------------
    def barrier(self) -> None:
        """Global barrier (parity: KVStore::Barrier)."""
        if self.num_workers > 1:
            from .parallel import collectives
            collectives.host_barrier()

    def _barrier(self):
        self.barrier()

    def num_dead_node(self, node_id: int = 0, timeout_sec: int = 60) -> int:
        """Parity: kvstore.h:338 — PJRT surfaces device failure as errors, so
        a live call implies zero dead nodes."""
        return 0

    def _send_command_to_servers(self, head, body) -> None:
        pass  # no server processes in the TPU design

    def save_optimizer_states(self, fname: str, dump_optimizer=False) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set")
        # crash-atomic like every other state writer (PR 5): a save
        # interrupted mid-write must not corrupt the previous states
        atomic_write(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _updater_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


_TYPES = ("local", "device", "local_allreduce_cpu", "local_allreduce_device",
          "nccl", "tpu_sync", "dist", "dist_sync", "dist_async",
          "dist_device_sync", "dist_sync_device")


def create(name: str = "local") -> KVStore:
    """Create a KVStore (parity: kvstore.cc:38 KVStore::Create)."""
    if not isinstance(name, str) or name not in _TYPES:
        raise MXNetError(f"unknown kvstore type {name}; known: {_TYPES}")
    return KVStore(name)
