"""Generic object registry helpers (parity: python/mxnet/registry.py —
`get_register_func` / `get_create_func` / `get_alias_func` build the
register()/create() surfaces that optimizer.py, initializer.py,
metric.py and lr_scheduler use; here they wrap `base.Registry`, the
same store those modules already register into)."""
from __future__ import annotations

import json

from .base import MXNetError, Registry

_REGISTRIES: dict = {}


def _registry(base_class, nickname: str) -> Registry:
    reg = _REGISTRIES.get(nickname)
    if reg is None:
        reg = _REGISTRIES[nickname] = Registry(nickname)
        reg.base_class = base_class
    return reg


def get_register_func(base_class, nickname: str):
    """-> register(klass, name=None) for this kind (reference
    registry.py register())."""
    reg = _registry(base_class, nickname)

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise MXNetError(
                f"can only register subclasses of "
                f"{base_class.__name__}, got {klass}")
        reg.register(klass, name=name or klass.__name__)
        return klass
    register.__doc__ = f"Register a new {nickname}."
    return register


def get_alias_func(base_class, nickname: str):
    """-> alias(name) class decorator (reference registry.py alias())."""
    reg = _registry(base_class, nickname)

    def alias(*names):
        def _do(klass):
            for n in names:
                reg.register(klass, name=n)
            return klass
        return _do
    return alias


def get_create_func(base_class, nickname: str):
    """-> create(spec, *args, **kwargs): by name, by (name, kwargs)
    json string, by instance passthrough (reference registry.py
    create())."""
    reg = _registry(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            if len(args) > 1 or kwargs:
                raise MXNetError(
                    f"{nickname} instance passthrough takes no extra "
                    "arguments")
            return args[0]
        if not args or not isinstance(args[0], str):
            raise MXNetError(
                f"create expects a {nickname} name or instance")
        name, rest = args[0], args[1:]
        if name.startswith("["):  # json ["name", {kwargs}] form
            spec = json.loads(name)
            name, kw = spec[0], (spec[1] if len(spec) > 1 else {})
            kw.update(kwargs)
            return reg.get(name)(*rest, **kw)
        return reg.get(name)(*rest, **kwargs)
    create.__doc__ = f"Create a {nickname} instance by name."
    return create
