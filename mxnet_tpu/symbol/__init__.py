"""`mx.sym` namespace (parity: python/mxnet/symbol/__init__.py)."""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     zeros, ones, arange)
from . import register
from .register import _gen, invoke_symbol

_g = globals()
for _name in dir(_gen):
    if not _name.startswith("__"):
        _g[_name] = getattr(_gen, _name)

# scalar/Symbol-dispatching free functions AFTER the op hoist so they
# shadow the raw generated wrappers (which don't take scalars)
from .symbol import pow, maximum, minimum, hypot  # noqa: E402

from . import graph
from .graph import GraphPlan
