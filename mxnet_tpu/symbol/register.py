"""Symbol op functions: `sym.<op>(...)` autogen from the shared op registry.

Parity: `python/mxnet/symbol/register.py` codegen.  Auto-creates missing
input variables (`fc1_weight`, `bn_moving_mean`, ...) exactly like the
reference's symbol composition, including aux-state tagging.
"""
from __future__ import annotations

import sys
import types

import numpy as _np

from ..base import MXNetError
from ..attribute import current_attrs
from ..name import NameManager
from ..ops import registry as _reg
from .symbol import Symbol, Variable, _Node, _truthy


def _auto_input_names(op, params):
    """Which declared inputs this node needs, given params."""
    names = list(op.input_names)
    p = dict(params)
    if op.name in ("FullyConnected", "Convolution", "Deconvolution"):
        no_bias = p.get("no_bias")
        if no_bias is None:
            # schema default decides (Deconvolution defaults no_bias=True)
            no_bias = op.schema.args["no_bias"].default
        if _truthy(no_bias):
            names.remove("bias")
    if op.name == "_contrib_ctc_loss":
        if not _truthy(p.get("use_data_lengths")):
            names.remove("data_lengths")
        if not _truthy(p.get("use_label_lengths")):
            names.remove("label_lengths")
    return names


def invoke_symbol(op_name: str, sym_inputs, kwargs, name=None, attr=None) -> Symbol:
    op = _reg.get_op(op_name)
    kwargs = dict(kwargs)
    kwargs.pop("ctx", None)
    name = name or kwargs.pop("name", None)
    attr = attr or kwargs.pop("attr", None)
    kwargs.pop("num_args", None)

    # split kwargs into symbol inputs vs op params
    named_inputs = {}
    params = {}
    for k, v in list(kwargs.items()):
        if isinstance(v, Symbol):
            named_inputs[k] = v
        elif v is not None:
            if k == "dtype" and not isinstance(v, str):
                v = _np.dtype(v).name
            params[k] = v

    hint = op_name.lower().lstrip("_")
    node_name = NameManager.current().get(name, hint)
    attrs = current_attrs(attr)

    if op.name == "Custom":
        # compose by the prop's declared arguments, auto-creating missing
        # ones as variables (parity: Custom loss layers get their
        # `<name>_label` variable created exactly like SoftmaxOutput)
        from ..ops.custom import _make_prop
        prop = _make_prop(dict(params))
        argnames = prop.list_arguments()
        extra_named = [k for k in named_inputs if k not in argnames]
        if extra_named:
            raise MXNetError(
                f"Custom({params.get('op_type')}): unknown symbol input(s) "
                f"{extra_named}; declared arguments are {argnames}")
        inputs = []
        pos = list(sym_inputs)
        for nm in argnames:
            if nm in named_inputs:
                inputs.append(named_inputs[nm]._entries[0])
            elif pos:
                inputs.append(pos.pop(0)._entries[0])
            else:
                inputs.append(Variable(f"{node_name}_{nm}")._entries[0])
        if pos:
            raise MXNetError(
                f"Custom({params.get('op_type')}): {len(sym_inputs)} "
                f"positional inputs but the prop declares only "
                f"{len(argnames)} arguments {argnames}")
        # unique node tag → one CustomOp instance per graph node (the
        # reference's one-operator-per-bound-node contract, custom.cc)
        params["__node__"] = node_name
    elif op.variadic:
        inputs = [s._entries[0] for s in sym_inputs]
        # variadic ops with optional extras (LeakyReLU prelu gamma)
        if op.name == "LeakyReLU" and params.get("act_type") == "prelu" \
                and len(inputs) == 1 and "gamma" not in named_inputs:
            gv = Variable(f"{node_name}_gamma")
            inputs.append(gv._entries[0])
        for k in ("gamma", "sequence_length"):
            if k in named_inputs:
                inputs.append(named_inputs[k]._entries[0])
        if any(a.name == "num_args" for a in op.schema.args.values()):
            params["num_args"] = len(inputs)
    else:
        needed = _auto_input_names(op, params)
        pos = list(sym_inputs)
        entries = {}
        for i, nm in enumerate(needed):
            if nm in named_inputs:
                entries[nm] = named_inputs[nm]._entries[0]
            elif pos:
                entries[nm] = pos.pop(0)._entries[0]
            else:
                entries[nm] = Variable(f"{node_name}_{nm}")._entries[0]
        inputs = [entries[nm] for nm in needed]

    node = _Node(op_name, node_name, params=params, inputs=inputs, attrs=attrs)
    n_out = node.num_outputs()
    return Symbol([(node, i) for i in range(n_out)]) if n_out > 1 else \
        Symbol([(node, 0)])


def _make_sym_func(op_name: str):
    op = _reg.get_op(op_name)

    def fn(*args, **kwargs):
        sym_inputs = []
        rest = list(args)
        while rest and isinstance(rest[0], Symbol):
            sym_inputs.append(rest.pop(0))
        if rest:
            names = [a for a in op.schema.args]
            taken = [n for n in names if n not in kwargs]
            for v, n in zip(rest, taken):
                kwargs[n] = v
        return invoke_symbol(op_name, sym_inputs, kwargs)

    fn.__name__ = op_name
    fn.__doc__ = op.docstring or f"Symbolic wrapper for operator '{op_name}'."
    return fn


def populate(module) -> None:
    for name in list(_reg.OP_REGISTRY) + list(_reg.OP_ALIASES):
        setattr(module, name, _make_sym_func(name))


_gen = types.ModuleType("mxnet_tpu.symbol._gen")
populate(_gen)
sys.modules["mxnet_tpu.symbol._gen"] = _gen


def _late_attach(op_name):
    """Frontend hook (registry.FRONTEND_ATTACH_HOOKS): expose an op
    registered after import on mx.sym immediately."""
    f = _make_sym_func(op_name)
    setattr(_gen, op_name, f)
    pkg = sys.modules.get("mxnet_tpu.symbol")
    if pkg is not None and not hasattr(pkg, op_name):
        setattr(pkg, op_name, f)


_reg.FRONTEND_ATTACH_HOOKS.append(_late_attach)
