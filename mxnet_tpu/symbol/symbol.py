"""Symbol: the declarative graph IR (parity: nnvm Symbol + python/mxnet/symbol).

Reference parity: `python/mxnet/symbol/symbol.py:53` (composition,
infer_shape/type, tojson/load, simple_bind/bind, Group, Variable) over the
NNVM graph (`src/nnvm/`, SURVEY.md §2.1).  TPU-native: the graph is a plain
python DAG; binding hands it to `mxnet_tpu.executor` which interprets it
inside one `jax.jit` — XLA performs what the reference's nnvm passes did
(shape/type propagation at trace time, PlanMemory, fusion, scheduling).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as _np

from ..base import MXNetError, np_dtype
from ..attribute import current_attrs
from ..name import NameManager
from ..ops import registry as _reg


class _Node:
    __slots__ = ("op", "name", "params", "inputs", "attrs")

    def __init__(self, op: Optional[str], name: str, params=None, inputs=None,
                 attrs=None):
        self.op = op              # None for variables
        self.name = name
        self.params = dict(params or {})
        self.inputs: List[Tuple["_Node", int]] = list(inputs or [])
        self.attrs = dict(attrs or {})

    @property
    def is_var(self) -> bool:
        return self.op is None

    def num_outputs(self) -> int:
        if self.is_var:
            return 1
        op = _reg.get_op(self.op)
        if self.op in ("SliceChannel", "split"):
            return int(dict(self.params).get("num_outputs", 1))
        if self.op == "Custom":
            from ..ops.custom import custom_num_outputs
            return custom_num_outputs(dict(self.params))
        if op.name == "RNN":
            return 3 if _truthy(self.params.get("state_outputs")) else 1
        if op.name in ("BatchNorm", "LayerNorm"):
            return 1  # mean/var exposed only via output_mean_var
        return max(op.num_outputs, 1)


def _truthy(v):
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return bool(v)


class Symbol:
    """An immutable handle to one or more output entries of the graph."""

    def __init__(self, entries: List[Tuple[_Node, int]]):
        self._entries = entries

    # -- composition --------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __getitem__(self, index):
        if isinstance(index, str):
            outputs = self.list_outputs()
            if index not in outputs:
                raise MXNetError(f"no output named {index}; have {outputs}")
            index = outputs.index(index)
        if isinstance(index, slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self):
        for i in range(len(self._entries)):
            yield self[i]

    def get_internals(self) -> "Symbol":
        """All intermediate outputs (parity: symbol.get_internals)."""
        entries = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- graph traversal ----------------------------------------------------
    def _topo(self) -> List[_Node]:
        seen = {}
        order: List[_Node] = []

        def visit(node: _Node):
            if id(node) in seen:
                return
            seen[id(node)] = True
            for src, _ in node.inputs:
                visit(src)
            order.append(node)

        for node, _ in self._entries:
            visit(node)
        return order

    def _aux_var_ids(self) -> set:
        aux = set()
        for node in self._topo():
            if node.is_var or node.op is None:
                continue
            op = _reg.get_op(node.op)
            for ai in op.aux_inputs:
                if ai < len(node.inputs):
                    src = node.inputs[ai][0]
                    if src.is_var:
                        aux.add(id(src))
        return aux

    def list_arguments(self) -> List[str]:
        aux = self._aux_var_ids()
        return [n.name for n in self._topo() if n.is_var and id(n) not in aux]

    def list_auxiliary_states(self) -> List[str]:
        aux = self._aux_var_ids()
        return [n.name for n in self._topo() if n.is_var and id(n) in aux]

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_var]

    def list_outputs(self) -> List[str]:
        outs = []
        for node, idx in self._entries:
            if node.is_var:
                outs.append(node.name)
            elif node.num_outputs() == 1:
                outs.append(node.name + "_output")
            else:
                outs.append(f"{node.name}_output{idx}")
        return outs

    def list_attr(self) -> Dict[str, str]:
        return dict(self._entries[0][0].attrs)

    def attr(self, key: str) -> Optional[str]:
        return self._entries[0][0].attrs.get(key)

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for node in self._topo():
            d = dict(node.attrs)
            if node.op is not None:
                d.update({k: str(v) for k, v in node.params.items() if v is not None})
            if d:
                out[node.name] = d
        return out

    def _set_attr(self, **kwargs):
        self._entries[0][0].attrs.update(kwargs)

    # -- call composition: net(data=other_sym) -------------------------------
    def __call__(self, *args, **kwargs) -> "Symbol":
        out = self.__copy__()
        out._compose(*args, **kwargs)
        return out

    def _compose(self, *args, **kwargs):
        name_map = {}
        if args:
            free = [n for n in self._topo() if n.is_var]
            for var, rep in zip(free, args):
                name_map[var.name] = rep
        name_map.update(kwargs)
        table = {}
        for node in self._topo():
            if node.is_var and node.name in name_map:
                table[id(node)] = name_map[node.name]._entries[0]
        if not table:
            return
        self._entries = [_substitute(e, table, {}) for e in self._entries]

    def __copy__(self):
        return Symbol(list(self._entries))

    def __deepcopy__(self, memo):
        table: Dict[int, Tuple[_Node, int]] = {}
        return Symbol([_substitute(e, {}, table, clone=True) for e in self._entries])

    # -- arithmetic -----------------------------------------------------------
    def _binary(self, other, op, scalar_op, rop=False):
        from . import register as _r
        if isinstance(other, Symbol):
            a, b = (other, self) if rop else (self, other)
            return _r.invoke_symbol(op, [a, b], {})
        return _r.invoke_symbol(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    def __radd__(self, o):
        return self.__add__(o)

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_rminus_scalar", rop=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, o):
        return self.__mul__(o)

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "_rdiv_scalar", rop=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return self._binary(-1.0, None, "_mul_scalar")

    def __hash__(self):
        return id(self)

    def __eq__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    # -- inference ------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        from .infer import infer_shape as _is
        return _is(self, partial, *args, **kwargs)

    def infer_type(self, *args, **kwargs):
        from .infer import infer_type as _it
        return _it(self, *args, **kwargs)

    # -- binding --------------------------------------------------------------
    def simple_bind(self, ctx, grad_req="write", type_dict=None, stype_dict=None,
                    group2ctx=None, shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        """Parity: symbol.py:1255 / MXExecutorSimpleBind — allocate arrays
        from inferred shapes and bind."""
        from ..executor import Executor
        from .. import ndarray as nd
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("cannot infer shapes for simple_bind")
        arg_types, _, aux_types = self.infer_type(**(type_dict or {}))
        args = {}
        for name, shp, dt in zip(self.list_arguments(), arg_shapes, arg_types):
            if shared_buffer is not None and name in shared_buffer and \
                    tuple(shared_buffer[name].shape) == tuple(shp):
                args[name] = shared_buffer[name]
            else:
                args[name] = nd.zeros(shp, ctx=ctx, dtype=dt)
                if shared_buffer is not None:
                    shared_buffer[name] = args[name]
        aux = {}
        for name, shp, dt in zip(self.list_auxiliary_states(), aux_shapes, aux_types):
            aux[name] = nd.zeros(shp, ctx=ctx, dtype=dt)
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in args}
        elif isinstance(grad_req, (list, tuple)):
            reqs = dict(zip(self.list_arguments(), grad_req))
        else:
            reqs = dict(grad_req)
        grads = {n: nd.zeros(args[n].shape, ctx=ctx, dtype=args[n].dtype)
                 for n in args if reqs.get(n, "null") != "null"}
        return Executor(self, ctx, args, grads, reqs, aux, group2ctx=group2ctx,
                        shared_exec=shared_exec)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        """Parity: symbol.py:1519 — bind to user-provided arrays."""
        from ..executor import Executor
        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        args_grad = args_grad or {}
        if isinstance(grad_req, str):
            reqs = {n: (grad_req if n in args_grad else "null") for n in arg_names}
            if not args_grad:
                reqs = {n: "null" for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            reqs = dict(zip(arg_names, grad_req))
        else:
            reqs = {n: grad_req.get(n, "null") for n in arg_names}
        aux = aux_states or {}
        if isinstance(aux, (list, tuple)):
            aux = dict(zip(self.list_auxiliary_states(), aux))
        return Executor(self, ctx, args, args_grad, reqs, aux,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context
        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # -- serialization ---------------------------------------------------------
    def tojson(self) -> str:
        """MXNet graph-JSON compatible serialization (parity: nnvm JSON)."""
        nodes = self._topo()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_var else n.op,
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.params.items() if v is not None}
                if n.params else {},
                "inputs": [[nid[id(s)], i, 0] for s, i in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_var]
        heads = [[nid[id(n)], i, 0] for n, i in self._entries]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10000]}}, indent=2)

    def save(self, fname: str) -> None:
        from ..base import atomic_write
        atomic_write(fname, self.tojson())

    def debug_str(self) -> str:
        lines = []
        for n in self._topo():
            kind = "Variable" if n.is_var else n.op
            ins = ", ".join(f"{s.name}[{i}]" for s, i in n.inputs)
            lines.append(f"{kind} {n.name}({ins})")
        return "\n".join(lines)


def _substitute(entry, table, memo, clone=False):
    node, idx = entry
    if id(node) in table:
        return (table[id(node)][0], idx if not node.is_var else table[id(node)][1])
    if id(node) in memo:
        return (memo[id(node)], idx)
    if node.is_var and not clone:
        return entry
    new_inputs = [_substitute(e, table, memo, clone) for e in node.inputs]
    if not clone and all(a is b for a, b in zip(new_inputs, node.inputs)):
        return entry
    nn = _Node(node.op, node.name, node.params, new_inputs, node.attrs)
    memo[id(node)] = nn
    return (nn, idx)


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------
def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs) -> Symbol:
    """Parity: symbol.var — free variable node with optional attr hints."""
    attrs = current_attrs(attr)
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype).name)
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    node = _Node(None, name, attrs=attrs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str: str) -> Symbol:
    """Load MXNet graph JSON (parity incl. reference-produced files for ops
    whose names/params match)."""
    g = json.loads(json_str)
    nodes: List[_Node] = []
    for jn in g["nodes"]:
        params = jn.get("attrs") or jn.get("param") or {}
        if jn["op"] == "null":
            node = _Node(None, jn["name"], attrs=params)
        else:
            inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
            node = _Node(jn["op"], jn["name"], params=params, inputs=inputs)
        nodes.append(node)
    heads = g.get("heads") or [[len(nodes) - 1, 0, 0]]
    return Symbol([(nodes[h[0]], h[1]) for h in heads])


def zeros(shape, dtype=None, **kwargs) -> Symbol:
    from . import register as _r
    return _r.invoke_symbol("_zeros", [], {"shape": shape, "dtype": dtype or "float32"})


def ones(shape, dtype=None, **kwargs) -> Symbol:
    from . import register as _r
    return _r.invoke_symbol("_ones", [], {"shape": shape, "dtype": dtype or "float32"})


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, **kwargs) -> Symbol:
    from . import register as _r
    return _r.invoke_symbol("_arange", [], {"start": start, "stop": stop,
                                            "step": step, "repeat": repeat,
                                            "dtype": dtype or "float32"})


def _binary_free_fn(op, scalar_op, rscalar_op, pyfn):
    """Scalar/Symbol-dispatching free function (parity: the symbol.py
    pow/maximum/minimum/hypot helpers, symbol/symbol.py:2524-2703)."""
    def fn(left, right):
        from . import register as _r
        lsym, rsym = isinstance(left, Symbol), isinstance(right, Symbol)
        if lsym and rsym:
            return _r.invoke_symbol(op, [left, right], {})
        if lsym:
            return _r.invoke_symbol(scalar_op, [left],
                                    {"scalar": float(right)})
        if rsym:
            return _r.invoke_symbol(rscalar_op, [right],
                                    {"scalar": float(left)})
        return pyfn(left, right)
    return fn


pow = _binary_free_fn("_power", "_power_scalar", "_rpower_scalar",
                      lambda a, b: a ** b)
maximum = _binary_free_fn("_maximum", "_maximum_scalar", "_maximum_scalar",
                          lambda a, b: a if a > b else b)
minimum = _binary_free_fn("_minimum", "_minimum_scalar", "_minimum_scalar",
                          lambda a, b: a if a < b else b)
hypot = _binary_free_fn("_hypot", "_hypot_scalar", "_hypot_scalar",
                        lambda a, b: (a * a + b * b) ** 0.5)
