"""Shape/type inference entry points (implementation in graph.py)."""
from .graph import infer_shape, infer_type, infer_shapes_types, GraphPlan
